"""Tests for the layer-zoo gap batch: spatial normalizations, locally
connected / connection-table convolutions, MV, GaussianSampler,
ResizeBilinear, Cropping3D, ConvLSTMPeephole3D, graph aliases.

Differential against torch CPU where torch has the same op (the
Torch7-oracle role, survey §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.core.table import Table



# heavyweight tier: differential oracles / trainers / registry sweeps;
# the quick tier is 'pytest -m "not slow"' (README Testing)
pytestmark = pytest.mark.slow

def run(module, x, training=False):
    from bigdl_tpu.nn.module import shape_of
    params, state, out_shape = module.build(jax.random.PRNGKey(0), shape_of(x))
    y, _ = module.apply(params, state, x, training=training,
                        rng=jax.random.PRNGKey(1))
    return y, params, out_shape


class TestSpatialNormalizations:
    def test_within_channel_lrn_formula(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 7, 7, 3))
        y, _, _ = run(nn.SpatialWithinChannelLRN(size=3, alpha=0.5, beta=0.75), x)
        # interior pixel: full 3x3 window
        win = x[0, 1:4, 1:4, 0]
        expect = x[0, 2, 2, 0] * (1 + 0.5 * jnp.mean(jnp.square(win))) ** -0.75
        np.testing.assert_allclose(float(y[0, 2, 2, 0]), float(expect), rtol=1e-5)

    def test_subtractive_constant_input_is_zeroed(self):
        # constant input: neighborhood mean == value everywhere (incl. borders
        # thanks to the coef correction), so output must be ~0
        x = jnp.full((1, 9, 9, 3), 2.5)
        y, _, _ = run(nn.SpatialSubtractiveNormalization(3), x)
        np.testing.assert_allclose(np.asarray(y), 0.0, atol=1e-5)

    def test_divisive_constant_input_is_ones(self):
        # constant input: local std == |value| everywhere -> output == 1
        x = jnp.full((1, 9, 9, 2), 3.0)
        y, _, _ = run(nn.SpatialDivisiveNormalization(2), x)
        np.testing.assert_allclose(np.asarray(y), 1.0, atol=1e-4)

    def test_contrastive_composes(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 8, 3))
        y, _, _ = run(nn.SpatialContrastiveNormalization(3), x)
        ys, _, _ = run(nn.SpatialSubtractiveNormalization(3), x)
        yd, _, _ = run(nn.SpatialDivisiveNormalization(3), ys)
        np.testing.assert_allclose(np.asarray(y), np.asarray(yd), rtol=1e-5)

    def test_normalize_scale(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 4, 8))
        y, params, _ = run(nn.NormalizeScale(scale=20.0), x)
        assert params["weight"].shape == (8,)
        norms = jnp.sqrt(jnp.sum(jnp.square(y / 20.0), axis=-1))
        np.testing.assert_allclose(np.asarray(norms), 1.0, rtol=1e-4)


class TestConnectionTableConv:
    def test_one_to_one_is_depthwise(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 3))
        m = nn.SpatialConvolutionMap(nn.one_to_one_connection_table(3), 3, 3)
        y, params, out_shape = run(m, x)
        assert y.shape == out_shape == (2, 6, 6, 3)
        # output channel o depends ONLY on input channel o
        w = params["weight"]
        mask = np.ones((3, 3)) - np.eye(3)
        assert float(jnp.sum(jnp.abs(w) * mask[None, None])) == 0.0

    def test_full_table_matches_spatial_convolution(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 3))
        m = nn.SpatialConvolutionMap(nn.full_connection_table(3, 5), 3, 3)
        params, state, _ = m.build(jax.random.PRNGKey(0), x.shape)
        ref = nn.SpatialConvolution(3, 5, 3, 3)
        y, _ = m.apply(params, state, x)
        y2, _ = ref.apply(params, {}, x)  # same param tree layout
        np.testing.assert_allclose(np.asarray(y), np.asarray(y2), rtol=1e-5)

    def test_random_table(self):
        m = nn.SpatialConvolutionMap(nn.random_connection_table(4, 6, 2), 3, 3)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 6, 6, 4))
        y, _, _ = run(m, x)
        assert y.shape == (1, 4, 4, 6)


class TestLocallyConnected:
    def test_2d_vs_torch(self):
        torch = pytest.importorskip("torch")
        # unshared-weights conv == conv2d_local; verify against an explicit
        # patch einsum in torch
        rs = np.random.RandomState(0)
        x = rs.randn(2, 7, 7, 3).astype(np.float32)
        m = nn.LocallyConnected2D(3, 7, 7, 4, 3, 3, with_bias=True)
        y, params, out_shape = run(m, jnp.asarray(x))
        assert y.shape == out_shape == (2, 5, 5, 4)
        # torch oracle: unfold -> per-position matmul.  torch unfold orders
        # features C-major like our realigned layout (C, kh, kw)
        tx = torch.from_numpy(np.moveaxis(x, -1, 1))  # NCHW
        patches = torch.nn.functional.unfold(tx, 3)  # (N, C*9, L)
        patches = patches.transpose(1, 2).reshape(2, 5, 5, 27)
        w = torch.from_numpy(np.asarray(params["weight"]))
        b = torch.from_numpy(np.asarray(params["bias"]))
        ty = torch.einsum("nhwk,hwko->nhwo", patches, w) + b
        np.testing.assert_allclose(np.asarray(y), ty.numpy(), rtol=1e-4, atol=1e-5)

    def test_2d_differs_across_positions(self):
        # same input patch at two positions must produce different outputs
        x = np.zeros((1, 6, 6, 1), np.float32)
        x[0, 0:3, 0:3, 0] = 1.0
        x[0, 3:6, 3:6, 0] = 1.0
        m = nn.LocallyConnected2D(1, 6, 6, 2, 3, 3, stride_w=3, stride_h=3,
                                  with_bias=False)
        y, _, _ = run(m, jnp.asarray(x))
        assert not np.allclose(np.asarray(y[0, 0, 0]), np.asarray(y[0, 1, 1]))

    def test_1d_shapes_and_locality(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 10, 4))
        m = nn.LocallyConnected1D(10, 4, 6, 3, stride_w=2)
        y, params, out_shape = run(m, x)
        assert y.shape == out_shape == (2, 4, 6)
        assert params["weight"].shape == (4, 12, 6)


class TestSmallGapLayers:
    def test_mv_batched(self):
        m = jax.random.normal(jax.random.PRNGKey(0), (3, 4, 5))
        v = jax.random.normal(jax.random.PRNGKey(1), (3, 5))
        y, _, _ = run(nn.MV(), Table(m, v))
        np.testing.assert_allclose(
            np.asarray(y), np.einsum("bnm,bm->bn", m, v), rtol=1e-5)

    def test_mv_trans(self):
        m = jax.random.normal(jax.random.PRNGKey(0), (4, 5))
        v = jax.random.normal(jax.random.PRNGKey(1), (4,))
        y, _, _ = run(nn.MV(trans=True), Table(m, v))
        np.testing.assert_allclose(np.asarray(y), np.asarray(m.T @ v), rtol=1e-5)

    def test_gaussian_sampler_stats(self):
        mean = jnp.full((4000,), 3.0)
        log_var = jnp.full((4000,), np.log(0.25))
        y, _, _ = run(nn.GaussianSampler(), Table(mean, log_var))
        assert abs(float(jnp.mean(y)) - 3.0) < 0.05
        assert abs(float(jnp.std(y)) - 0.5) < 0.05

    def test_gaussian_sampler_grad_flows(self):
        # reparameterisation: d/dmean == 1, d/dlogvar == 0.5*eps*exp(.5 lv)
        sampler = nn.GaussianSampler()

        def f(mean, lv):
            y, _ = sampler.apply({}, {}, Table(mean, lv),
                                 rng=jax.random.PRNGKey(7))
            return jnp.sum(y)

        g_mean, g_lv = jax.grad(f, argnums=(0, 1))(jnp.zeros(8), jnp.zeros(8))
        np.testing.assert_allclose(np.asarray(g_mean), 1.0)
        assert float(jnp.sum(jnp.abs(g_lv))) > 0.0

    def test_resize_bilinear_vs_torch(self):
        torch = pytest.importorskip("torch")
        rs = np.random.RandomState(0)
        x = rs.randn(2, 5, 7, 3).astype(np.float32)
        for align in (False, True):
            y, _, _ = run(nn.ResizeBilinear(10, 14, align_corners=align),
                          jnp.asarray(x))
            tx = torch.from_numpy(np.moveaxis(x, -1, 1))
            ty = torch.nn.functional.interpolate(
                tx, size=(10, 14), mode="bilinear", align_corners=align)
            ty = np.moveaxis(ty.numpy(), 1, -1)
            if align:
                np.testing.assert_allclose(np.asarray(y), ty, rtol=1e-4, atol=1e-5)
            else:
                # half_pixel vs TF's legacy asymmetric mapping differ at
                # non-sample points; both agree on shape and range
                assert y.shape == ty.shape

    def test_resize_bilinear_identity(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 6, 6, 2))
        y, _, _ = run(nn.ResizeBilinear(6, 6), x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(x))

    def test_cropping3d(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 6, 7, 8, 3))
        y, _, out_shape = run(nn.Cropping3D((1, 2), (0, 1), (2, 2)), x)
        assert y.shape == out_shape == (2, 3, 6, 4, 3)
        np.testing.assert_allclose(np.asarray(y),
                                   np.asarray(x[:, 1:4, 0:6, 2:6, :]))

    def test_graph_aliases(self):
        assert nn.StaticGraph is nn.Graph and nn.DynamicGraph is nn.Graph


class TestConvLSTM3D:
    def test_shapes_and_recurrence(self):
        cell = nn.ConvLSTMPeephole3D(2, 4, 3, 3)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 4, 4, 4, 2))
        rec = nn.Recurrent(cell)
        params, state, out_shape = rec.build(jax.random.PRNGKey(0),
                                             (2, 5, 4, 4, 4, 2))
        y, _ = rec.apply(params, state, x)
        assert y.shape == (2, 5, 4, 4, 4, 4)

    def test_no_peephole(self):
        cell = nn.ConvLSTMPeephole3D(2, 3, 3, 3, with_peephole=False)
        params, _, _ = cell.build(jax.random.PRNGKey(0), (1, 4, 4, 4, 2))
        assert "peep" not in params


class TestReviewRegressions:
    def test_mv_output_shape_tuple_input(self):
        assert nn.MV().output_shape(((2, 3, 4), (2, 4))) == (2, 3)
        assert nn.MV(trans=True).output_shape(((2, 3, 4), (2, 3))) == (2, 4)

    def test_keras_zeropadding2d_nested_form(self):
        import bigdl_tpu.keras as keras
        layer = keras.ZeroPadding2D(((1, 2), (3, 4)))
        m = layer._make((2, 4, 5, 3))
        y, _, _ = run(m, jnp.zeros((2, 4, 5, 3)))
        assert y.shape == (2, 7, 12, 3)

    def test_random_connection_table_varies(self):
        a = nn.random_connection_table(8, 8, 4)
        b = nn.random_connection_table(8, 8, 4)
        c = nn.random_connection_table(8, 8, 4, seed=5)
        d = nn.random_connection_table(8, 8, 4, seed=5)
        assert c == d
        assert a != b or a != c  # fresh entropy (overwhelmingly likely)


class TestConnectionTableWidening:
    def test_unused_top_input_features(self):
        """A random table may leave the highest input features unconnected
        (torch nn.tables.random allows it); the conv must still accept the
        full-width input, including after a serializer round trip."""
        from bigdl_tpu.utils.serializer import module_from_spec, module_to_spec

        table = [(0, o) for o in range(3)] + [(1, o) for o in range(3)]
        m = nn.SpatialConvolutionMap(table, 3, 3)  # inputs 2,3 unused
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 6, 6, 4))
        p, s, _ = m.build(jax.random.PRNGKey(1), (1, 6, 6, 4))
        y, _ = m.apply(p, s, x)
        assert y.shape == (1, 4, 4, 3)
        m2 = module_from_spec(module_to_spec(m))
        y2, _ = m2.apply(p, s, x)  # reloaded module, widened params
        np.testing.assert_allclose(np.asarray(y2), np.asarray(y))
