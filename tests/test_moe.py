"""Mixture-of-Experts tests (beyond-reference: survey §2.10 records expert
parallelism absent in BigDL; the `expert` mesh axis implements it here)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.core.engine import AXIS_DATA, AXIS_EXPERT, Engine
from jax.sharding import NamedSharding, PartitionSpec as P



# heavyweight tier: differential oracles / trainers / registry sweeps;
# the quick tier is 'pytest -m "not slow"' (README Testing)
pytestmark = pytest.mark.slow

def _moe(d=8, e=4, k=1, **kw):
    m = nn.MoE(d, e, k=k, mlp_ratio=2, **kw)
    p, s, _ = m.build(jax.random.PRNGKey(0), (2, 6, d))
    return m, p, s


class TestMoERouting:
    def test_output_shape_and_determinism(self):
        m, p, s = _moe()
        x = jnp.asarray(np.random.RandomState(0).rand(2, 6, 8), jnp.float32)
        y1, _ = m.apply(p, s, x)
        y2, _ = m.apply(p, s, x)
        assert y1.shape == x.shape
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))

    def test_top1_matches_manual_expert(self):
        """With huge capacity, each token's output must equal its argmax
        expert's MLP applied to it, gated by the RAW router probability
        (Switch semantics y = p_i(x) * E_i(x) — the gate carries the
        router's task-loss gradient)."""
        m, p, s = _moe(e=3, k=1, capacity_factor=8.0)
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.rand(1, 5, 8), jnp.float32)
        y, _ = m.apply(p, s, x)
        xt = np.asarray(x).reshape(5, 8)
        probs = np.asarray(jax.nn.softmax(
            xt @ np.asarray(p["router"]["weight"]), axis=-1))
        choice = np.argmax(probs, -1)
        for t in range(5):
            e_ = int(choice[t])
            h = jax.nn.gelu(xt[t] @ np.asarray(p["experts"]["fc1_w"][e_])
                            + np.asarray(p["experts"]["fc1_b"][e_]))
            want = probs[t, e_] * (h @ np.asarray(p["experts"]["fc2_w"][e_])
                                   + np.asarray(p["experts"]["fc2_b"][e_]))
            np.testing.assert_allclose(np.asarray(y)[0, t], want, atol=1e-5)

    def test_top1_router_gets_task_gradient(self):
        """Regression: with k=1 the combine gate must NOT be renormalized
        to 1.0 — the router learns from the task loss through the gate."""
        m, p, s = _moe(e=4, k=1, aux_weight=0.0)
        x = jnp.asarray(np.random.RandomState(5).rand(2, 8, 8), jnp.float32)

        def loss(p_):
            y, _ = m.apply(p_, s, x, training=True)
            return jnp.sum(jnp.square(y))

        g = jax.grad(loss)(p)
        assert float(jnp.max(jnp.abs(g["router"]["weight"]))) > 0.0

    def test_capacity_drops_overflow_tokens(self):
        """capacity 1 with all tokens preferring one expert: only one token
        is served; dropped tokens output zero (residual carries them)."""
        m, p, s = _moe(e=2, k=1, capacity_factor=1e-9)
        # force router to always pick expert 0
        p["router"]["weight"] = jnp.zeros_like(p["router"]["weight"]
                                               ).at[:, 0].set(5.0)
        x = jnp.asarray(np.random.RandomState(2).rand(1, 6, 8), jnp.float32)
        assert m.capacity(6) == 1
        y, _ = m.apply(p, s, x)
        nonzero_rows = np.asarray(jnp.any(jnp.abs(y[0]) > 1e-9, axis=-1))
        assert nonzero_rows.sum() == 1  # exactly the first arriving token

    def test_top2_combines_two_experts(self):
        m, p, s = _moe(e=4, k=2, capacity_factor=8.0)
        x = jnp.asarray(np.random.RandomState(3).rand(2, 4, 8), jnp.float32)
        y, _ = m.apply(p, s, x)
        assert y.shape == x.shape
        # compare against dense mixture over the top-2 experts
        xt = np.asarray(x).reshape(8, 8)
        probs = np.asarray(jax.nn.softmax(
            xt @ np.asarray(p["router"]["weight"]), -1))
        got = np.asarray(y).reshape(8, 8)
        for t in range(8):
            top2 = np.argsort(probs[t])[-2:][::-1]
            w = probs[t][top2] / probs[t][top2].sum()
            want = np.zeros(8, np.float32)
            for wi, e_ in zip(w, top2):
                h = jax.nn.gelu(xt[t] @ np.asarray(p["experts"]["fc1_w"][e_])
                                + np.asarray(p["experts"]["fc1_b"][e_]))
                want += wi * (h @ np.asarray(p["experts"]["fc2_w"][e_])
                              + np.asarray(p["experts"]["fc2_b"][e_]))
            np.testing.assert_allclose(got[t], want, atol=1e-4)

    def test_aux_loss_gradient_reaches_router(self):
        m, p, s = _moe(e=4, k=1, aux_weight=0.1)
        x = jnp.asarray(np.random.RandomState(4).rand(2, 8, 8), jnp.float32)

        def loss(p_):
            y, _ = m.apply(p_, s, x, training=True)
            return jnp.sum(y * 0.0)  # main loss contributes nothing

        g = jax.grad(loss)(p)
        # only the aux (load-balance) term can produce router gradient here
        assert float(jnp.max(jnp.abs(g["router"]["weight"]))) > 0.0
        m0, p0, s0 = _moe(e=4, k=1, aux_weight=0.0)
        g0 = jax.grad(lambda p_: jnp.sum(
            m0.apply(p_, s0, x, training=True)[0] * 0.0))(p0)
        assert float(jnp.max(jnp.abs(g0["router"]["weight"]))) == 0.0


class TestMoEExpertParallel:
    def test_expert_sharded_train_step(self):
        """dp+ep: batch over 'data', experts over 'expert' — one jitted
        step with XLA-inserted all-to-alls; loss must decrease."""
        from bigdl_tpu.optim import Adam

        mesh = Engine.build_mesh(devices=jax.devices(),
                                 **{AXIS_DATA: 2, AXIS_EXPERT: 4})
        m = nn.MoE(8, 4, k=1, mlp_ratio=2, capacity_factor=4.0)
        params, s, _ = m.build(jax.random.PRNGKey(0), (8, 4, 8))
        rules = {
            ("experts", "fc1_w"): P(AXIS_EXPERT, None, None),
            ("experts", "fc1_b"): P(AXIS_EXPERT, None),
            ("experts", "fc2_w"): P(AXIS_EXPERT, None, None),
            ("experts", "fc2_b"): P(AXIS_EXPERT, None),
            ("router", "weight"): P(),
        }
        params = {
            a: {b: jax.device_put(v, NamedSharding(mesh, rules[(a, b)]))
                for b, v in sub.items()}
            for a, sub in params.items()}

        rs = np.random.RandomState(0)
        w_true = rs.rand(8, 8).astype(np.float32)
        x = rs.rand(8, 4, 8).astype(np.float32)
        y = x @ w_true
        optim = Adam(learning_rate=1e-2)
        opt_state = optim.init(params)
        xd = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(AXIS_DATA)))
        yd = jax.device_put(jnp.asarray(y), NamedSharding(mesh, P(AXIS_DATA)))

        @jax.jit
        def step(p, os_):
            def loss_fn(p):
                out, _ = m.apply(p, s, xd, training=True)
                return jnp.mean((out - yd) ** 2)

            l, g = jax.value_and_grad(loss_fn)(p)
            p2, os2 = optim.step(g, p, os_)
            return p2, os2, l

        with jax.set_mesh(mesh):
            losses = []
            for _ in range(60):
                params, opt_state, l = step(params, opt_state)
                losses.append(float(l))
        assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
        # expert weights actually sharded
        assert AXIS_EXPERT in str(params["experts"]["fc1_w"].sharding.spec)

    def test_transformer_lm_with_moe(self):
        from bigdl_tpu.models import TransformerLM

        lm = TransformerLM(vocab_size=64, hidden_size=32, n_layer=2, n_head=4,
                           moe_experts=4, scan_layers=True)
        p, s, _ = lm.build(jax.random.PRNGKey(0), (2, 8))
        x = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 8)))
        y, _ = lm.apply(p, s, x)
        assert y.shape == (2, 8, 64)
        assert np.isfinite(np.asarray(y)).all()
        # scan stacking put a leading layer dim on expert params
        assert p["blocks"]["mlp"]["experts"]["fc1_w"].shape[0] == 2

    def test_scanned_moe_training_grad(self):
        """Regression: the aux-loss custom_vjp must survive inside the
        scan-over-layers trace (a closure over a tracer does not)."""
        from bigdl_tpu.models import TransformerLM

        lm = TransformerLM(vocab_size=32, hidden_size=16, n_layer=2, n_head=2,
                           moe_experts=4, moe_k=2, scan_layers=True)
        p, s, _ = lm.build(jax.random.PRNGKey(0), (2, 4))
        x = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 4)))

        @jax.jit
        def loss(p_):
            out, _ = lm.apply(p_, {}, x, training=True,
                              rng=jax.random.PRNGKey(1))
            return -jnp.mean(out)

        g = jax.grad(loss)(p)
        for leaf in jax.tree_util.tree_leaves(g):
            assert np.isfinite(np.asarray(leaf)).all()
        assert float(jnp.max(jnp.abs(g["blocks"]["mlp"]["router"]["weight"]))) > 0

    def test_aux_frac_is_pre_capacity_drop(self):
        """Regression: the load-balance fraction must reflect the router's
        assignment BEFORE capacity dropping, or the penalty saturates at
        capacity/T exactly when one expert is overloaded."""
        m, p, s = _moe(e=2, k=1, capacity_factor=1e-9, aux_weight=1.0)
        p["router"]["weight"] = jnp.zeros_like(p["router"]["weight"]
                                               ).at[:, 0].set(5.0)
        x = jnp.asarray(np.random.RandomState(6).rand(1, 8, 8), jnp.float32)

        def loss(p_):
            y, _ = m.apply(p_, s, x, training=True)
            return jnp.sum(y * 0.0)

        g = jax.grad(loss)(p)["router"]["weight"]
        # aux gradient must push column 0 DOWN relative to column 1 with the
        # full frac=1.0 weight, even though only 1 of 8 tokens was served
        col_diff = float(jnp.mean(g[:, 0] - g[:, 1]))
        # d(aux)/d(logit) via softmax: proportional to frac difference
        assert col_diff != 0.0
        m2, p2, s2 = _moe(e=2, k=1, capacity_factor=8.0, aux_weight=1.0)
        p2["router"]["weight"] = jnp.zeros_like(p2["router"]["weight"]
                                                ).at[:, 0].set(5.0)
        g2 = jax.grad(lambda p_: jnp.sum(
            m2.apply(p_, s2, x, training=True)[0] * 0.0))(p2)["router"]["weight"]
        # same routing fractions -> same aux gradient regardless of capacity
        np.testing.assert_allclose(np.asarray(g), np.asarray(g2), atol=1e-6)
