"""Sparse (VarLen) ParseExample host path: TFRecord shards with
variable-length feature lists -> SparseFeature records -> SparseMiniBatch
-> SparseLinear / LookupTableSparse training.

Reference: utils/tf/loaders/ParseExample.scala + nn/tf/ParsingOps.scala
(VarLen features parse to COO SparseTensors feeding the wide-and-deep
models); here parsing runs host-side and densifies per encoding at the
batch boundary (static shapes for jit).
"""

import numpy as np
import pytest

import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset import VarLenFeature
from bigdl_tpu.dataset.sample import SparseFeature
from bigdl_tpu.dataset.tfrecord import ParsedExampleDataSet, TFRecordWriter
from bigdl_tpu.nn.tf_ops import build_example_proto
from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger

# heavyweight tier: differential oracles / trainers / registry sweeps;
# the quick tier is 'pytest -m "not slow"' (README Testing)
pytestmark = pytest.mark.slow


VOCAB, CLASSES, MAXLEN, BATCH, N = 24, 3, 6, 8, 96


def _write_varlen_records(tmp_path, n=N, seed=0):
    """Each record: VarLen int64 "ids" (1..MAXLEN ids); label = the class
    of its FIRST id (ids are drawn from per-class vocab ranges so both
    the multi-hot and the embedding-bag model can recover the class)."""
    rs = np.random.RandomState(seed)
    path = str(tmp_path / "sparse.tfrecord")
    all_ids, labels = [], []
    per_class = VOCAB // CLASSES
    with TFRecordWriter(path) as w:
        for i in range(n):
            c = i % CLASSES
            k = rs.randint(1, MAXLEN + 1)
            ids = rs.randint(c * per_class, (c + 1) * per_class,
                             size=k).astype(np.int64)
            w.write(build_example_proto(
                {"ids": ids, "y": np.asarray([c], np.int64)}))
            all_ids.append(ids)
            labels.append(c)
    return path, all_ids, np.asarray(labels)


class TestVarLenParsing:
    def test_multi_hot_batches(self, tmp_path):
        path, all_ids, labels = _write_varlen_records(tmp_path)
        ds = ParsedExampleDataSet(
            [path], batch_size=BATCH, dense_keys=["y"], dense_shapes=[()],
            label_key="y", sparse_features=[
                VarLenFeature("ids", VOCAB, dtype="float32",
                              encoding="multi_hot")])
        batches = list(ds.data(train=False))
        assert len(batches) == N // BATCH
        b0 = batches[0]
        x = np.asarray(b0.input)
        assert x.shape == (BATCH, VOCAB)
        for r in range(BATCH):
            want = np.zeros(VOCAB, np.float32)
            for i in all_ids[r]:
                want[i] += 1.0
            np.testing.assert_array_equal(x[r], want)
        np.testing.assert_array_equal(
            np.asarray(b0.target).ravel(), labels[:BATCH])

    def test_positions_encoding_pads_id_bags(self, tmp_path):
        path, all_ids, _ = _write_varlen_records(tmp_path)
        ds = ParsedExampleDataSet(
            [path], batch_size=BATCH, dense_keys=["y"], dense_shapes=[()],
            label_key="y", feature_padding=-1, sparse_features=[
                VarLenFeature("ids", MAXLEN, encoding="positions")])
        x = np.asarray(next(iter(ds.data(train=False))).input)
        assert x.shape == (BATCH, MAXLEN)
        for r in range(BATCH):
            k = len(all_ids[r])
            np.testing.assert_array_equal(x[r, :k], all_ids[r])
            assert np.all(x[r, k:] == -1)

    def test_oversize_record_is_loud(self):
        f = VarLenFeature("ids", 2, encoding="positions")
        with pytest.raises(ValueError, match="declared size"):
            f.to_sparse(np.arange(5))
        m = VarLenFeature("ids", 4, encoding="multi_hot")
        with pytest.raises(ValueError, match="out of range"):
            m.to_sparse(np.asarray([7]))

    def test_sparse_feature_pad_fill(self):
        sf = SparseFeature(np.asarray([[0], [2]]), np.asarray([5, 9]), (4,))
        np.testing.assert_array_equal(sf.to_dense(-1), [5, -1, 9, -1])


class TestSparseTraining:
    def test_sparse_linear_trains_from_shard(self, tmp_path):
        """Wide model: multi-hot VarLen ids -> SparseLinear -> classes."""
        path, _, labels = _write_varlen_records(tmp_path)
        ds = ParsedExampleDataSet(
            [path], batch_size=BATCH, dense_keys=["y"], dense_shapes=[()],
            label_key="y", sparse_features=[
                VarLenFeature("ids", VOCAB, dtype="float32",
                              encoding="multi_hot")])
        model = nn.Sequential(nn.SparseLinear(VOCAB, CLASSES),
                              nn.LogSoftMax())
        opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                              optim_method=SGD(learning_rate=0.5),
                              end_trigger=Trigger.max_epoch(12))
        opt.optimize()
        xs = np.stack([np.asarray(b.input)
                       for b in ds.data(train=False)]).reshape(-1, VOCAB)
        out, _ = model.apply(opt.params, opt.model_state, jnp.asarray(xs))
        acc = float((np.argmax(np.asarray(out), -1) == labels).mean())
        assert acc >= 0.95, acc

    def test_lookup_table_sparse_trains_from_shard(self, tmp_path):
        """Deep model: padded id bags -> LookupTableSparse(mean) ->
        Linear -> classes."""
        path, _, labels = _write_varlen_records(tmp_path)
        ds = ParsedExampleDataSet(
            [path], batch_size=BATCH, dense_keys=["y"], dense_shapes=[()],
            label_key="y", feature_padding=-1, sparse_features=[
                VarLenFeature("ids", MAXLEN, encoding="positions")])
        emb = 8
        model = nn.Sequential(
            nn.LookupTableSparse(VOCAB, emb, combiner="mean"),
            nn.Linear(emb, CLASSES), nn.LogSoftMax())
        opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                              optim_method=SGD(learning_rate=0.5),
                              end_trigger=Trigger.max_epoch(25))
        opt.optimize()
        xs = np.concatenate([np.asarray(b.input)
                             for b in ds.data(train=False)])
        out, _ = model.apply(opt.params, opt.model_state, jnp.asarray(xs))
        acc = float((np.argmax(np.asarray(out), -1) == labels).mean())
        assert acc >= 0.9, acc
