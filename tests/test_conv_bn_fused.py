"""Fused 1x1 conv + BN-stats training kernel: numerical parity with the
unfused Sequential(SpatialConvolution, SpatialBatchNormalization) pair —
forward, running-state update, gradients, eval mode — plus the pallas
kernel itself (interpret mode) and the resnet50(fuse_bn=True) wiring.

Reference role: nn/mkldnn/Fusion.scala:26-31 (conv+bn is the reference's
marquee fusion; the training-side stats fusion here is the TPU-native
equivalent, BENCH_APPENDIX.md's named lever)."""

import numpy as np

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
import pytest

from bigdl_tpu.ops.conv_bn_stats import (_dense_matmul_stats,
                                         conv1x1_bn_stats, matmul_bn_stats)

# heavyweight tier: differential oracles / trainers / registry sweeps;
# the quick tier is 'pytest -m "not slow"' (README Testing)
pytestmark = pytest.mark.slow

N, H, W, CIN, COUT = 4, 8, 8, 16, 32


def _pair_model(stride=1, zero_gamma=False):
    conv = nn.SpatialConvolution(CIN, COUT, 1, 1, stride, stride, 0, 0,
                                 with_bias=False)
    bn = nn.SpatialBatchNormalization(COUT)
    return nn.Sequential(conv, bn)


def _sync_params(fused_params, pair, pair_params):
    pair_params = jax.tree_util.tree_map(lambda v: v, pair_params)
    names = list(pair.children)
    pair_params[names[0]]["weight"] = fused_params["weight"]
    pair_params[names[1]]["weight"] = fused_params["gamma"]
    pair_params[names[1]]["bias"] = fused_params["beta"]
    return pair_params


class TestKernel:
    def test_pallas_matches_dense(self):
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(200, 48).astype(np.float32))
        w = jnp.asarray(rs.randn(48, 96).astype(np.float32))
        y1, a1, b1 = matmul_bn_stats(x, w, block_m=128, block_n=64,
                                     block_k=32, interpret=True)
        y0, a0, b0 = _dense_matmul_stats(x, w)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(a1), np.asarray(a0),
                                   rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(np.asarray(b1), np.asarray(b0),
                                   rtol=1e-4, atol=1e-3)

    def test_custom_vjp_matches_autodiff(self):
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.randn(96, 24).astype(np.float32))
        w = jnp.asarray(rs.randn(24, 40).astype(np.float32))

        def loss(fn):
            def f(x, w):
                y, s1, s2 = fn(x, w)
                return (jnp.sum(jnp.tanh(y)) + jnp.sum(s1) * 0.1
                        + jnp.sum(jnp.sqrt(s2 + 1.0)))

            return f

        pallas_fn = lambda x, w: matmul_bn_stats(  # noqa: E731
            x, w, block_m=32, block_n=32, block_k=8, interpret=True)
        g1 = jax.grad(loss(pallas_fn), argnums=(0, 1))(x, w)
        g0 = jax.grad(loss(_dense_matmul_stats), argnums=(0, 1))(x, w)
        for a, b in zip(g1, g0):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_strided_conv_path(self):
        rs = np.random.RandomState(2)
        x = jnp.asarray(rs.randn(2, 8, 8, 6).astype(np.float32))
        w = jnp.asarray(rs.randn(1, 1, 6, 10).astype(np.float32))
        y, s1, s2 = conv1x1_bn_stats(x, w, stride=2)
        assert y.shape == (2, 4, 4, 10)
        yf = np.asarray(y)
        np.testing.assert_allclose(np.asarray(s1), yf.sum((0, 1, 2)),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(s2), (yf * yf).sum((0, 1, 2)),
                                   rtol=1e-5)


class TestFusedModuleParity:
    def _build_both(self, stride=1, zero_gamma=False, seed=0):
        fused = nn.SpatialConvolutionBN(CIN, COUT, stride=stride,
                                        zero_gamma=zero_gamma)
        pair = _pair_model(stride, zero_gamma)
        key = jax.random.PRNGKey(seed)
        fp, fs, _ = fused.build(key, (N, H, W, CIN))
        pp, ps, _ = pair.build(key, (N, H, W, CIN))
        pp = _sync_params(fp, pair, pp)
        return fused, fp, fs, pair, pp, ps

    def test_training_forward_and_state(self):
        fused, fp, fs, pair, pp, ps = self._build_both()
        rs = np.random.RandomState(3)
        x = jnp.asarray(rs.randn(N, H, W, CIN).astype(np.float32))
        yf, sf = fused.apply(fp, fs, x, training=True)
        yp, sp = pair.apply(pp, ps, x, training=True)
        np.testing.assert_allclose(np.asarray(yf), np.asarray(yp),
                                   rtol=1e-4, atol=1e-5)
        bn_name = list(pair.children)[1]
        for k in ("running_mean", "running_var"):
            np.testing.assert_allclose(np.asarray(sf[k]),
                                       np.asarray(sp[bn_name][k]),
                                       rtol=1e-4, atol=1e-6)

    def test_training_forward_strided(self):
        fused, fp, fs, pair, pp, ps = self._build_both(stride=2)
        rs = np.random.RandomState(4)
        x = jnp.asarray(rs.randn(N, H, W, CIN).astype(np.float32))
        yf, _ = fused.apply(fp, fs, x, training=True)
        yp, _ = pair.apply(pp, ps, x, training=True)
        assert yf.shape == yp.shape == (N, H // 2, W // 2, COUT)
        np.testing.assert_allclose(np.asarray(yf), np.asarray(yp),
                                   rtol=1e-4, atol=1e-5)

    def test_gradient_parity(self):
        fused, fp, fs, pair, pp, ps = self._build_both()
        rs = np.random.RandomState(5)
        x = jnp.asarray(rs.randn(N, H, W, CIN).astype(np.float32))
        t = jnp.asarray(rs.randn(N, H, W, COUT).astype(np.float32))

        def loss_fused(p):
            y, _ = fused.apply(p, fs, x, training=True)
            return jnp.mean((y - t) ** 2)

        def loss_pair(p):
            y, _ = pair.apply(p, ps, x, training=True)
            return jnp.mean((y - t) ** 2)

        gf = jax.grad(loss_fused)(fp)
        gp = jax.grad(loss_pair)(pp)
        names = list(pair.children)
        np.testing.assert_allclose(np.asarray(gf["weight"]),
                                   np.asarray(gp[names[0]]["weight"]),
                                   rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gf["gamma"]),
                                   np.asarray(gp[names[1]]["weight"]),
                                   rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gf["beta"]),
                                   np.asarray(gp[names[1]]["bias"]),
                                   rtol=1e-3, atol=1e-5)

    def test_eval_mode_uses_running_stats(self):
        fused, fp, fs, pair, pp, ps = self._build_both()
        rs = np.random.RandomState(6)
        # make running stats non-trivial first
        x = jnp.asarray(rs.randn(N, H, W, CIN).astype(np.float32))
        _, fs = fused.apply(fp, fs, x, training=True)
        _, ps = pair.apply(pp, ps, x, training=True)
        xe = jnp.asarray(rs.randn(N, H, W, CIN).astype(np.float32))
        ye_f, fs2 = fused.apply(fp, fs, xe, training=False)
        ye_p, _ = pair.apply(pp, ps, xe, training=False)
        np.testing.assert_allclose(np.asarray(ye_f), np.asarray(ye_p),
                                   rtol=1e-4, atol=1e-5)
        assert fs2 is fs  # eval does not touch state


class TestResNetFuseBn:
    def test_resnet50_fuse_bn_trains_a_step(self):
        from bigdl_tpu.models import resnet50

        model = resnet50(class_num=16, fuse_bn=True)

        def walk(m):
            yield m
            for c in getattr(m, "children", {}).values():
                yield from walk(c)

        fused = [m for m in walk(model)
                 if isinstance(m, nn.SpatialConvolutionBN)]
        # Fusion is restricted to convs whose output width is a multiple
        # of the 8-sublane tile at stride 1 (w=56 stage): elsewhere the
        # kernel's NHWC boundary costs retiling copies that were measured
        # to exceed the stats-read savings on chip (BENCH_APPENDIX.md).
        # stage0: 3 blocks x (reduce+expand) + 1 stride-1 shortcut = 7,
        # plus stage1 block0's reduce conv (input still 56) = 8.
        assert len(fused) == 8, len(fused)
        params, state, _ = model.build(jax.random.PRNGKey(0), (2, 32, 32, 3))
        x = jnp.asarray(np.random.RandomState(0)
                        .randn(2, 32, 32, 3).astype(np.float32))
        yt = jnp.asarray(np.arange(2) % 16)
        crit = nn.ClassNLLCriterion()

        def loss(p):
            out, new_state = model.apply(p, state, x, training=True)
            return crit.forward(out, yt), new_state

        (lv, new_state), grads = jax.value_and_grad(loss, has_aux=True)(params)
        assert np.isfinite(float(lv))
        gmax = max(float(jnp.max(jnp.abs(g)))
                   for g in jax.tree_util.tree_leaves(grads))
        assert np.isfinite(gmax) and gmax > 0


class TestServingFold:
    def test_fold_fused_module_matches_eval(self):
        """A TRAINED SpatialConvolutionBN folds into one plain 1x1 conv
        for serving (utils/fusion.fold_batchnorm), matching eval-mode
        output exactly — the full train-fused -> serve-folded story."""
        from bigdl_tpu.utils.fusion import fold_batchnorm

        rs = np.random.RandomState(0)
        model = nn.Sequential(nn.SpatialConvolutionBN(CIN, COUT, stride=2),
                              nn.ReLU())
        params, state, _ = model.build(jax.random.PRNGKey(0), (N, H, W, CIN))
        # move params/stats off init so the fold is non-trivial
        key = list(model.children)[0]
        params[key]["gamma"] = jnp.asarray(rs.rand(COUT).astype(np.float32) + 0.5)
        params[key]["beta"] = jnp.asarray(rs.randn(COUT).astype(np.float32))
        x = jnp.asarray(rs.randn(N, H, W, CIN).astype(np.float32))
        _, state = model.apply(params, state, x, training=True)

        fm, fp, fs = fold_batchnorm(model, params, state)
        assert not any(isinstance(m, nn.SpatialConvolutionBN)
                       for m in fm.flattened_modules())
        xe = jnp.asarray(rs.randn(N, H, W, CIN).astype(np.float32))
        want, _ = model.apply(params, state, xe, training=False)
        got, _ = fm.apply(fp, fs, xe, training=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    def test_fold_resnet50_fuse_bn_graph_blocks(self):
        """resnet50(fuse_bn=True) folds end to end: every
        SpatialConvolutionBN inside the bottleneck Graphs becomes a plain
        conv, outputs match eval mode."""
        from bigdl_tpu.models import resnet50
        from bigdl_tpu.utils.fusion import fold_batchnorm

        model = resnet50(class_num=8, fuse_bn=True)
        params, state, _ = model.build(jax.random.PRNGKey(1), (2, 32, 32, 3))
        rs = np.random.RandomState(2)
        x = jnp.asarray(rs.rand(2, 32, 32, 3).astype(np.float32))
        _, state = model.apply(params, state, x, training=True)

        fm, fp, fs = fold_batchnorm(model, params, state)
        remaining = [m for m in fm.flattened_modules()
                     if isinstance(m, nn.SpatialConvolutionBN)]
        assert not remaining
        xe = jnp.asarray(rs.rand(2, 32, 32, 3).astype(np.float32))
        want, _ = model.apply(params, state, xe, training=False)
        got, _ = fm.apply(fp, fs, xe, training=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-3, atol=1e-4)

    def test_fold_unwraps_remat_blocks(self):
        """resnet50(fuse_bn=True, remat=True): the serving fold unwraps
        nn.Remat (a training-only device) and folds the inner blocks."""
        from bigdl_tpu.models import resnet50
        from bigdl_tpu.utils.fusion import fold_batchnorm

        model = resnet50(class_num=8, fuse_bn=True, remat=True)
        params, state, _ = model.build(jax.random.PRNGKey(1), (2, 32, 32, 3))
        rs = np.random.RandomState(3)
        x = jnp.asarray(rs.rand(2, 32, 32, 3).astype(np.float32))
        _, state = model.apply(params, state, x, training=True)
        fm, fp, fs = fold_batchnorm(model, params, state)
        assert not any(isinstance(m, (nn.SpatialConvolutionBN, nn.Remat))
                       for m in fm.flattened_modules())
        want, _ = model.apply(params, state, x, training=False)
        got, _ = fm.apply(fp, fs, x, training=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-3, atol=1e-4)
