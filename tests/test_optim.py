"""Optimizer/schedule/trigger/validation tests.

Models the reference's RefOptimizer-oracle strategy (survey §4): optimizers
are differentially tested against torch.optim on identical quadratic
problems; end-to-end convergence is tested on a small classification task
(the DistriOptimizerSpec analogue), including the 8-virtual-device mesh.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.core.engine import Engine
from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch
from bigdl_tpu.optim import (

    SGD, Adam, Adadelta, Adagrad, Adamax, Ftrl, RMSprop, Trigger,
    Top1Accuracy, Loss,
)

# heavyweight tier: differential oracles / trainers / registry sweeps;
# the quick tier is 'pytest -m "not slow"' (README Testing)
pytestmark = pytest.mark.slow



def quad_problem():
    """min ||Wx - b||^2 toy problem shared with the torch oracle."""
    rs = np.random.RandomState(0)
    w0 = rs.randn(4, 3).astype(np.float32)
    return {"w": jnp.asarray(w0)}, w0


def run_ours(method, steps=20):
    params, w0 = quad_problem()
    target = jnp.ones((4, 3))
    opt_state = method.init(params)

    def loss_fn(p):
        return jnp.sum(jnp.square(p["w"] - target))

    for _ in range(steps):
        grads = jax.grad(loss_fn)(params)
        params, opt_state = method.step(grads, params, opt_state)
    return np.asarray(params["w"])


def run_torch(torch, opt_cls, steps=20, **kwargs):
    _, w0 = quad_problem()
    w = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    opt = opt_cls([w], **kwargs)
    target = torch.ones(4, 3)
    for _ in range(steps):
        opt.zero_grad()
        loss = ((w - target) ** 2).sum()
        loss.backward()
        opt.step()
    return w.detach().numpy()


class TestOptimMethodsVsTorch:
    def test_sgd_momentum(self):
        torch = pytest.importorskip("torch")
        ours = run_ours(SGD(learning_rate=0.05, momentum=0.9, dampening=0.0))
        theirs = run_torch(torch, torch.optim.SGD, lr=0.05, momentum=0.9)
        np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)

    def test_sgd_nesterov_weight_decay(self):
        torch = pytest.importorskip("torch")
        ours = run_ours(SGD(learning_rate=0.05, momentum=0.9, dampening=0.0,
                            nesterov=True, weight_decay=0.01))
        theirs = run_torch(torch, torch.optim.SGD, lr=0.05, momentum=0.9,
                           nesterov=True, weight_decay=0.01)
        np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)

    def test_adam(self):
        torch = pytest.importorskip("torch")
        ours = run_ours(Adam(learning_rate=0.1))
        theirs = run_torch(torch, torch.optim.Adam, lr=0.1)
        # fp32 rounding drifts accumulate over 20 steps near sqrt cancellation
        np.testing.assert_allclose(ours, theirs, rtol=2e-3, atol=2e-3)

    def test_adamax(self):
        torch = pytest.importorskip("torch")
        ours = run_ours(Adamax(learning_rate=0.1, epsilon=1e-8))
        theirs = run_torch(torch, torch.optim.Adamax, lr=0.1)
        np.testing.assert_allclose(ours, theirs, rtol=1e-3, atol=1e-4)

    def test_adagrad(self):
        torch = pytest.importorskip("torch")
        ours = run_ours(Adagrad(learning_rate=0.1))
        theirs = run_torch(torch, torch.optim.Adagrad, lr=0.1)
        np.testing.assert_allclose(ours, theirs, rtol=1e-3, atol=1e-4)

    def test_adadelta_vs_torch(self):
        torch = pytest.importorskip("torch")
        ours = run_ours(Adadelta(decay_rate=0.9, epsilon=1e-6), steps=20)
        theirs = run_torch(torch, torch.optim.Adadelta, lr=1.0, rho=0.9, eps=1e-6)
        np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-5)

    def test_rmsprop_ftrl_converge(self):
        # no exact torch twin for the reference formulations; check descent
        _, w0 = quad_problem()
        init_err = np.mean(np.abs(w0 - 1.0))
        for m, steps, factor in [(RMSprop(learning_rate=0.05), 200, 0.35),
                                 (Ftrl(learning_rate=0.5), 200, 0.35)]:
            w = run_ours(m, steps=steps)
            err = np.mean(np.abs(w - 1.0))
            assert err < factor * init_err, f"{type(m).__name__}: {err} vs {init_err}"


class TestSchedules:
    def test_poly_step_multistep(self):
        lr = optim.Poly(0.5, 100)(1.0, jnp.asarray(0), 0)
        np.testing.assert_allclose(float(lr), 1.0)
        lr = optim.Poly(0.5, 100)(1.0, jnp.asarray(75), 0)
        np.testing.assert_allclose(float(lr), 0.5, atol=1e-6)
        lr = optim.Step(10, 0.5)(1.0, jnp.asarray(25), 0)
        np.testing.assert_allclose(float(lr), 0.25)
        ms = optim.MultiStep([10, 20], 0.1)
        np.testing.assert_allclose(float(ms(1.0, jnp.asarray(15), 0)), 0.1, rtol=1e-5)
        np.testing.assert_allclose(float(ms(1.0, jnp.asarray(25), 0)), 0.01, rtol=1e-5)

    def test_warmup_then_decay(self):
        s = optim.EpochDecayWithWarmUp(5, 0.1, lambda e: jnp.floor(e / 30.0))
        np.testing.assert_allclose(float(s(0.1, 0, jnp.asarray(0))), 0.1, rtol=1e-6)
        np.testing.assert_allclose(float(s(0.1, 0, jnp.asarray(3))), 0.4, rtol=1e-6)
        np.testing.assert_allclose(float(s(0.1, 0, jnp.asarray(10))), 0.5, rtol=1e-6)
        np.testing.assert_allclose(float(s(0.1, 0, jnp.asarray(35))), 0.05, rtol=1e-6)

    def test_plateau(self):
        p = optim.Plateau(factor=0.5, patience=2, mode="min")
        for score in [1.0, 1.0, 1.0]:
            p.on_score(score)
        np.testing.assert_allclose(float(p(1.0, 0, 0)), 0.5)

    def test_sgd_default_decay_matches_reference_formula(self):
        m = SGD(learning_rate=1.0, learning_rate_decay=0.1)
        st = m.init({"w": jnp.zeros(1)})
        for expected in [1.0, 1.0 / 1.1, 1.0 / 1.2]:
            lr = float(m.current_lr(st))
            np.testing.assert_allclose(lr, expected, rtol=1e-6)
            _, st = m.step({"w": jnp.zeros(1)}, {"w": jnp.zeros(1)}, st)


class TestTrigger:
    def test_triggers(self):
        s = {"epoch": 3, "neval": 10, "loss": 0.5, "score": 0.9,
             "epoch_finished": True}
        assert Trigger.every_epoch()(s)
        assert Trigger.several_iteration(5)(s)
        assert not Trigger.several_iteration(3)(s)
        assert Trigger.max_epoch(3)(s)
        assert not Trigger.max_epoch(4)(s)
        assert Trigger.min_loss(0.6)(s)
        assert Trigger.max_score(0.8)(s)
        assert Trigger.and_(Trigger.max_epoch(3), Trigger.min_loss(0.6))(s)
        assert Trigger.or_(Trigger.max_epoch(99), Trigger.min_loss(0.6))(s)


class TestValidationMethods:
    def test_top1_top5(self):
        out = jnp.asarray(np.eye(6, 10, dtype=np.float32))
        target = jnp.arange(6)
        v, c = Top1Accuracy().batch(out, target)
        assert float(v) == 6 and int(c) == 6
        target2 = jnp.asarray([0, 1, 2, 3, 4, 9])
        v, _ = Top1Accuracy().batch(out, target2)
        assert float(v) == 5
        v5, _ = optim.Top5Accuracy().batch(out, target2)
        assert float(v5) >= 5

    def test_hit_ratio_ndcg(self):
        # positive at col 0; score 0.9 vs noise below => rank 0
        out = jnp.asarray([[0.9, 0.1, 0.2], [0.1, 0.9, 0.05]])
        hr, c = optim.HitRatio(k=1).batch(out, None)
        assert float(hr) == 1.0 and int(c) == 2
        nd, _ = optim.NDCG(k=2).batch(out, None)
        assert 0.5 < float(nd) <= 2.0


def make_classification_dataset(n=256, dim=8, classes=4, batch=32, seed=0):
    # class centers are FIXED across seeds; `seed` only varies the noise, so
    # train/val sets come from the same distribution
    centers = np.random.RandomState(1234).randn(classes, dim).astype(np.float32) * 3
    rs = np.random.RandomState(seed)
    xs, ys = [], []
    for i in range(n):
        c = i % classes
        xs.append(centers[c] + rs.randn(dim).astype(np.float32) * 0.3)
        ys.append(c)
    samples = [Sample.from_ndarray(x, np.int32(y)) for x, y in zip(xs, ys)]
    return ArrayDataSet(samples).transform(SampleToMiniBatch(batch))


class TestTrainingLoop:
    def test_local_optimizer_convergence(self, tmp_path):
        ds = make_classification_dataset()
        model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4),
                              nn.LogSoftMax())
        o = optim.LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                                 optim_method=SGD(learning_rate=0.5),
                                 end_trigger=Trigger.max_epoch(5))
        o.set_validation(Trigger.every_epoch(), make_classification_dataset(seed=1),
                         [Top1Accuracy()])
        o.set_checkpoint(str(tmp_path / "ckpt"), Trigger.every_epoch())
        from bigdl_tpu.utils import TrainSummary
        o.set_train_summary(TrainSummary(str(tmp_path), "test"))
        o.optimize()
        acc = o.validate()[0].result()[0]
        assert acc > 0.9, f"accuracy {acc}"
        # summary written and readable
        scalars = o.train_summary.read_scalar("Loss")
        assert len(scalars) > 0
        # checkpoint written
        from bigdl_tpu.utils import latest_checkpoint
        assert latest_checkpoint(str(tmp_path / "ckpt")) is not None

    def test_distri_optimizer_8_devices(self):
        assert jax.device_count() == 8
        Engine.reset()
        Engine.init()
        ds = make_classification_dataset(batch=32)  # 32 % 8 == 0
        model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4),
                              nn.LogSoftMax())
        o = optim.DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                                  optim_method=Adam(learning_rate=0.05),
                                  end_trigger=Trigger.max_epoch(4))
        o.set_validation(Trigger.every_epoch(), make_classification_dataset(seed=1),
                         [Top1Accuracy()])
        o.optimize()
        acc = o.validate()[0].result()[0]
        assert acc > 0.9, f"accuracy {acc}"

    def test_distri_matches_local(self):
        """Same seed => mesh training equals single-device training
        (the determinism the reference can't get from its async straggler
        dropping)."""
        from bigdl_tpu.core.random import RandomGenerator

        results = []
        for mesh in [None, Engine.build_mesh(data=8)]:
            RandomGenerator.set_seed(7)
            ds = make_classification_dataset(batch=32)
            model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4),
                                  nn.LogSoftMax())
            o = optim.Optimizer(model, ds, nn.ClassNLLCriterion(),
                                optim_method=SGD(learning_rate=0.1),
                                mesh=mesh, end_trigger=Trigger.max_epoch(1))
            o.optimize()
            results.append(jax.tree_util.tree_map(np.asarray, o.params))
        flat0 = jax.tree_util.tree_leaves(results[0])
        flat1 = jax.tree_util.tree_leaves(results[1])
        for a, b in zip(flat0, flat1):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_checkpoint_resume(self, tmp_path):
        from bigdl_tpu.core.random import RandomGenerator

        RandomGenerator.set_seed(3)
        ds = make_classification_dataset()
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                              nn.LogSoftMax())
        o = optim.LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                                 optim_method=SGD(learning_rate=0.2),
                                 end_trigger=Trigger.max_epoch(2))
        o.set_checkpoint(str(tmp_path / "ck"), Trigger.every_epoch())
        o.optimize()
        # resume into a fresh optimizer, train 1 more epoch
        model2 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                               nn.LogSoftMax())
        o2 = optim.LocalOptimizer(model2, ds, nn.ClassNLLCriterion(),
                                  optim_method=SGD(learning_rate=0.2),
                                  end_trigger=Trigger.max_epoch(3))
        o2.resume_from(str(tmp_path / "ck"))
        o2.optimize()
        assert o2._driver_state["epoch"] == 3
        assert o2._driver_state["neval"] > o._driver_state["neval"]

    def test_validate_recompiles_on_method_swap(self):
        """Swapping val_methods must not reuse the stale jitted eval
        closure (regression: _compiled was cached unconditionally)."""
        ds = make_classification_dataset(n=64)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                              nn.LogSoftMax())
        o = optim.LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                                 optim_method=SGD(learning_rate=0.5),
                                 end_trigger=Trigger.max_epoch(2))
        o.set_validation(Trigger.every_epoch(),
                         make_classification_dataset(n=64, seed=1),
                         [Top1Accuracy()])
        o.optimize()
        acc = o.validate()[0].result()[0]
        assert 0.0 <= acc <= 1.0
        # swap to a Loss method: the value must be an NLL mean (a per-record
        # average < the accuracy COUNT the stale closure would produce)
        o.val_methods = [optim.Loss(nn.ClassNLLCriterion())]
        res = o.validate()[0]
        assert res.name == "Loss"
        loss_val = res.result()[0]
        # with a >90%-accurate model the stale Top1 closure would return a
        # per-batch *count* (>= 1 per batch summed); a real NLL mean on this
        # converged model is well below 1
        assert loss_val < 0.9, f"stale eval closure suspected: {loss_val}"

    def test_checkpoint_missing_files(self, tmp_path):
        from bigdl_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

        params = {"w": np.ones((2, 2), np.float32)}
        opt_template = {"m": np.full((2, 2), 7.0, np.float32)}
        # save WITHOUT opt_state: loading with a template must yield None,
        # not a zero-filled tree that silently corrupts optimizer slots
        d = save_checkpoint(str(tmp_path), 1, params)
        p, ms, os_, drv = load_checkpoint(d, params, None, opt_template)
        assert os_ is None
        np.testing.assert_allclose(p["w"], params["w"])
        # a dir with no params.npz at all is a broken checkpoint: raise
        bad = tmp_path / "ckpt_9"
        bad.mkdir()
        (bad / "meta.json").write_text('{"schema_version": 1, "driver_state": {}}')
        with pytest.raises(FileNotFoundError):
            load_checkpoint(str(bad), params)

    def test_live_per_layer_profile(self, tmp_path):
        """profile=True surfaces per-layer fwd/bwd times through Metrics
        and the TrainSummary (reference: AbstractModule getTimes)."""
        from bigdl_tpu.utils import TrainSummary

        ds = make_classification_dataset(n=64)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                              nn.LogSoftMax())
        o = optim.LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                                 optim_method=SGD(learning_rate=0.1),
                                 end_trigger=Trigger.max_epoch(1))
        o.set_train_summary(TrainSummary(str(tmp_path), "prof"))
        o.set_profile()
        o.optimize()
        layer_metrics = [k for k in o.metrics._sums
                         if k.startswith("layer ")]
        assert any("forward" in k for k in layer_metrics), layer_metrics
        assert any("backward" in k for k in layer_metrics), layer_metrics
        scalars = o.train_summary.read_scalar(
            f"LayerTime/{model[0].name}/forward_ms")
        assert len(scalars) == 1

    def test_gradient_clipping(self):
        from bigdl_tpu.optim.parameter_processor import (
            ConstantClippingProcessor, L2NormClippingProcessor)
        g = {"a": jnp.asarray([3.0, -4.0]), "b": jnp.asarray([0.5])}
        clipped = ConstantClippingProcessor(-1.0, 1.0).process(g)
        np.testing.assert_allclose(np.asarray(clipped["a"]), [1.0, -1.0])
        l2 = L2NormClippingProcessor(1.0).process(g)
        norm = np.sqrt(sum(np.sum(np.square(np.asarray(v))) for v in
                           jax.tree_util.tree_leaves(l2)))
        np.testing.assert_allclose(norm, 1.0, rtol=1e-5)


class TestLBFGS:
    def test_rosenbrock_converges(self):
        from bigdl_tpu.optim import LBFGS

        def rosenbrock(p):
            x, y = p["x"], p["y"]
            return (1 - x) ** 2 + 100 * (y - x ** 2) ** 2

        feval = jax.jit(jax.value_and_grad(rosenbrock))
        params = {"x": jnp.asarray(-1.2), "y": jnp.asarray(1.0)}
        opt = LBFGS(max_iter=60, max_eval=500)
        new_params, hist = opt.optimize(feval, params)
        assert hist[-1] < 1e-6
        assert abs(float(new_params["x"]) - 1.0) < 1e-3
        assert abs(float(new_params["y"]) - 1.0) < 1e-3

    def test_quadratic_no_line_search(self):
        from bigdl_tpu.optim import LBFGS

        A = jnp.asarray(np.diag([1.0, 10.0, 100.0]), jnp.float32)
        b = jnp.asarray([1.0, -2.0, 3.0])

        def quad(x):
            return 0.5 * x @ A @ x - b @ x

        feval = jax.jit(jax.value_and_grad(quad))
        x0 = jnp.zeros(3)
        opt = LBFGS(max_iter=50, line_search=False, learning_rate=1.0)
        x, hist = opt.optimize(feval, x0)
        x_star = jnp.linalg.solve(A, b)
        assert hist[-1] < float(quad(x_star)) + 1e-4


class TestParallelOptimizer:
    """reference: optim/ParallelOptimizer.scala:580 (layer-wise overlapped
    gradient sync) — here a shard_map step with per-leaf pmean collectives."""

    def _data(self, n=64, f=8, classes=4, batch=16):
        from bigdl_tpu.dataset import DataSet, MiniBatch

        rs = np.random.RandomState(0)
        xs = rs.rand(n, f).astype(np.float32)
        ys = rs.randint(0, classes, n)
        batches = [MiniBatch(xs[i:i + batch], ys[i:i + batch])
                   for i in range(0, n, batch)]
        return DataSet.array(batches), xs, ys

    def test_matches_pjit_optimizer(self):
        """ParallelOptimizer must land on the same weights as the pjit
        DistriOptimizer — same math, different collective schedule."""
        import jax
        from bigdl_tpu.core.engine import Engine
        from bigdl_tpu.core.random import RandomGenerator
        from bigdl_tpu.optim import (DistriOptimizer, ParallelOptimizer, SGD,
                                     Trigger)

        mesh = Engine.build_mesh(devices=jax.devices(), data=8)

        def train(cls):
            # fresh dataset per run: ArrayDataSet's epoch counter drives the
            # seeded shuffle, so both runs must start at epoch 0
            ds, _, _ = self._data()
            RandomGenerator.set_seed(7)
            model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                  nn.Linear(16, 4), nn.LogSoftMax())
            opt = cls(model, ds, nn.ClassNLLCriterion(),
                      optim_method=SGD(learning_rate=0.1, momentum=0.9),
                      mesh=mesh, end_trigger=Trigger.max_epoch(2))
            opt.optimize()
            return opt.params

        p1 = train(DistriOptimizer)
        p2 = train(ParallelOptimizer)
        for a, b in zip(jax.tree_util.tree_leaves(p1),
                        jax.tree_util.tree_leaves(p2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-5)

    def test_composes_with_tensor_parallel(self):
        """sharding_rules on ParallelOptimizer: the 'data' axis stays
        MANUAL (per-leaf overlapped gradient psums) while tp axes run
        under GSPMD — same weights as the DistriOptimizer tp path, with
        the fc genuinely sharded over 'model'."""
        import jax
        from jax.sharding import PartitionSpec as P

        from bigdl_tpu.core.engine import AXIS_DATA, AXIS_MODEL, Engine
        from bigdl_tpu.core.random import RandomGenerator
        from bigdl_tpu.optim import (DistriOptimizer, ParallelOptimizer,
                                     SGD, Trigger)
        from bigdl_tpu.parallel import ShardingRules

        mesh = Engine.build_mesh(devices=jax.devices(),
                                 **{AXIS_DATA: 4, AXIS_MODEL: 2})

        def train(cls):
            ds, _, _ = self._data()
            RandomGenerator.set_seed(9)
            model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                  nn.Linear(16, 4), nn.LogSoftMax())
            rules = (ShardingRules()
                     .add(r"^2/weight$", P(None, AXIS_MODEL))
                     .add(r"^2/bias$", P(AXIS_MODEL)))
            opt = cls(model, ds, nn.ClassNLLCriterion(),
                      optim_method=SGD(learning_rate=0.1, momentum=0.9),
                      mesh=mesh, sharding_rules=rules,
                      end_trigger=Trigger.max_epoch(2))
            opt.optimize()
            return opt

        o1 = train(DistriOptimizer)
        o2 = train(ParallelOptimizer)
        fc = o2.params["2"]["weight"]
        assert AXIS_MODEL in str(fc.sharding.spec), fc.sharding.spec
        for a, b in zip(jax.tree_util.tree_leaves(o1.params),
                        jax.tree_util.tree_leaves(o2.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-5)

    def test_sync_bn_enabled(self):
        import jax
        from bigdl_tpu.core.engine import AXIS_DATA, Engine
        from bigdl_tpu.optim import ParallelOptimizer, SGD, Trigger

        mesh = Engine.build_mesh(devices=jax.devices(), data=8)
        ds, _, _ = self._data()
        model = nn.Sequential(nn.Linear(8, 16), nn.BatchNormalization(16),
                              nn.ReLU(), nn.Linear(16, 4), nn.LogSoftMax())
        opt = ParallelOptimizer(model, ds, nn.ClassNLLCriterion(),
                                optim_method=SGD(learning_rate=0.05),
                                mesh=mesh, end_trigger=Trigger.max_epoch(1))
        bn = list(model.children.values())[1]
        assert bn.axis_name is None  # construction must not mutate the model
        opt.optimize()
        assert np.isfinite(opt._driver_state["loss"])
        # sync-BN (setParallism analogue) is scoped to the run: the axis
        # name is restored so the model still trains under plain jit
        assert bn.axis_name is None
        from bigdl_tpu.optim import LocalOptimizer

        ds2, _, _ = self._data()
        opt2 = LocalOptimizer(model, ds2, nn.ClassNLLCriterion(),
                              optim_method=SGD(learning_rate=0.05),
                              end_trigger=Trigger.max_epoch(1))
        opt2.optimize()
        assert np.isfinite(opt2._driver_state["loss"])


class TestProfiling:
    """reference: survey §5.1 (getTimes per-layer timing)."""

    def test_layer_times_and_summary(self):
        from bigdl_tpu.optim import layer_times
        from bigdl_tpu.optim.profiling import summarize

        model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
        params, state, _ = model.build(jax.random.PRNGKey(0), (4, 8))
        x = jnp.asarray(np.random.RandomState(0).rand(4, 8), jnp.float32)
        times = layer_times(model, params, state, x, iters=2, warmup=0)
        assert [t.name for t in times] == [m.name for m in model.children.values()]
        assert all(t.forward_s > 0 for t in times)
        # parameter-bearing layers got a backward measurement
        assert times[0].backward_s > 0 and times[2].backward_s > 0
        assert times[1].backward_s == 0.0  # ReLU: no params
        table = summarize(times)
        assert "fwd ms" in table and times[0].name in table

    def test_profiler_trace_noop_safe(self, tmp_path):
        from bigdl_tpu.optim import profiler_trace

        with profiler_trace(str(tmp_path / "trace")):
            _ = jnp.sum(jnp.ones((4, 4)))


class TestRegularizer:
    """reference: optim/Regularizer.scala (wRegularizer/bRegularizer added
    to the gradient inside accGradParameters)."""

    def test_grad_and_penalty(self):
        from bigdl_tpu.optim import L1L2Regularizer, L1Regularizer, L2Regularizer

        p = jnp.asarray([-2.0, 0.5, 3.0])
        np.testing.assert_allclose(L2Regularizer(0.1).grad(p), 0.1 * p)
        np.testing.assert_allclose(L1Regularizer(0.3).grad(p),
                                   0.3 * np.sign(p))
        r = L1L2Regularizer(0.3, 0.1)
        np.testing.assert_allclose(r.grad(p), 0.3 * np.sign(p) + 0.1 * p)
        assert float(r.penalty(p)) == pytest.approx(
            0.3 * 5.5 + 0.05 * float(jnp.sum(p * p)))

    def test_collect_walks_containers(self):
        from bigdl_tpu.optim import L2Regularizer
        from bigdl_tpu.optim.regularizer import collect_regularizers

        reg = L2Regularizer(0.01)
        m = nn.Sequential(
            nn.Linear(4, 8, w_regularizer=reg),
            nn.Sequential(nn.Linear(8, 8, b_regularizer=reg)),
            nn.Linear(8, 2))
        found = collect_regularizers(m)
        assert len(found) == 2
        paths = {(p, k) for p, k, _ in found}
        assert (("0",), "weight") in paths
        # nested container path
        assert any(k == "bias" and len(p) == 2 for p, k, _ in found)

    def test_trainer_applies_regularizer(self):
        """L2 on a layer must shrink its weights vs an unregularized run."""
        from bigdl_tpu.dataset import DataSet, MiniBatch
        from bigdl_tpu.optim import L2Regularizer, LocalOptimizer, SGD, Trigger
        from bigdl_tpu.core.random import RandomGenerator

        rs = np.random.RandomState(0)
        x = rs.rand(32, 6).astype(np.float32)
        y = rs.randint(0, 3, 32)

        def train(reg):
            RandomGenerator.set_seed(11)
            model = nn.Sequential(nn.Linear(6, 3, w_regularizer=reg),
                                  nn.LogSoftMax())
            ds = DataSet.array([MiniBatch(x, y)])
            opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                                 optim_method=SGD(learning_rate=0.1),
                                 end_trigger=Trigger.max_epoch(30))
            opt.optimize()
            return float(jnp.sum(jnp.square(opt.params["0"]["weight"])))

        assert train(L2Regularizer(0.5)) < 0.7 * train(None)

    def test_serializer_roundtrip_with_regularizer(self, tmp_path):
        from bigdl_tpu.optim import L1L2Regularizer
        from bigdl_tpu.utils import load_model, save_model

        m = nn.Sequential(nn.Linear(4, 2,
                                    w_regularizer=L1L2Regularizer(0.1, 0.2)))
        p, s, _ = m.build(jax.random.PRNGKey(0), (2, 4))
        path = str(tmp_path / "reg_model")
        save_model(path, m, p, s)
        m2, p2, s2 = load_model(path)
        reg = list(m2.children.values())[0].w_regularizer
        assert reg is not None and reg.l1 == 0.1 and reg.l2 == 0.2


class TestTriggerDeterminism:
    def test_deterministic_flags(self):
        from bigdl_tpu.optim import Trigger

        assert Trigger.every_epoch().deterministic
        assert Trigger.several_iteration(5).deterministic
        assert Trigger.max_epoch(3).deterministic
        assert not Trigger.min_loss(0.1).deterministic
        assert not Trigger.max_score(0.9).deterministic
        assert Trigger.and_(Trigger.max_epoch(2),
                            Trigger.every_epoch()).deterministic
        assert not Trigger.or_(Trigger.every_epoch(),
                               Trigger.min_loss(0.1)).deterministic
        # user-constructed triggers default to the SAFE broadcast path
        assert not Trigger(lambda s: s["loss"] < 0.1, "custom").deterministic
        # plain callables compose (classified non-deterministic)
        mixed = Trigger.and_(Trigger.every_epoch(),
                             lambda s: s["neval"] % 7 == 0)
        assert not mixed.deterministic
        assert mixed({"epoch_finished": True, "neval": 7})


class TestRemoteCheckpoint:
    def test_memory_scheme_roundtrip(self):
        """fsspec-routed checkpoint path (memory:// stands in for gs://
        hdfs:// s3:// — the reference's utils/File remote-path parity)."""
        import numpy as np
        pytest.importorskip("fsspec")
        from bigdl_tpu.utils import checkpoint as ck

        params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
        d = ck.save_checkpoint("memory://ckpts/run1", 3, params,
                               driver_state={"epoch": 1})
        assert d == "memory://ckpts/run1/ckpt_3"
        assert ck.latest_checkpoint("memory://ckpts/run1") == d
        loaded, _, _, drv = ck.load_checkpoint(
            d, {"w": np.zeros((2, 3), np.float32)})
        np.testing.assert_allclose(loaded["w"], params["w"])
        assert drv["epoch"] == 1

    def test_interrupted_save_does_not_block_resume(self, tmp_path):
        """A ckpt dir without meta.json (killed mid-save) is skipped and
        the previous intact checkpoint resumes."""
        import numpy as np
        from bigdl_tpu.utils import checkpoint as ck

        params = {"w": np.ones((2, 2), np.float32)}
        good = ck.save_checkpoint(str(tmp_path), 5, params)
        (tmp_path / "ckpt_9").mkdir()  # interrupted: no meta.json
        assert ck.latest_checkpoint(str(tmp_path)) == good


class TestDeterminism:
    def test_training_is_bit_deterministic(self):
        """Two runs from the same seed produce IDENTICAL weights — the
        TPU-native replacement for the reference's mersenne-twister seeding
        story (utils/RandomGenerator.scala); threefry keys + jit make runs
        reproducible by construction."""
        import numpy as np
        import jax

        import bigdl_tpu.nn as nn
        from bigdl_tpu.core.random import RandomGenerator
        from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch
        from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger

        def run_once():
            RandomGenerator.set_seed(123)
            rs = np.random.RandomState(7)
            x = rs.randn(64, 6).astype("float32")
            y = (x.sum(1) > 0).astype("int32")
            ds = ArrayDataSet([Sample.from_ndarray(a, b)
                               for a, b in zip(x, y)]
                              ).transform(SampleToMiniBatch(16))
            model = nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Dropout(0.2),
                                  nn.Linear(8, 2), nn.LogSoftMax())
            opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                                 optim_method=SGD(learning_rate=0.1),
                                 end_trigger=Trigger.max_epoch(2))
            opt.optimize()
            return [np.asarray(l) for l in
                    jax.tree_util.tree_leaves(opt.params)]

        a = run_once()
        b = run_once()
        for la, lb in zip(a, b):
            np.testing.assert_array_equal(la, lb)


class TestBinaryAccuracy:
    def test_thresholded_counting(self):
        import jax.numpy as jnp
        from bigdl_tpu.optim import BinaryAccuracy

        m = BinaryAccuracy()
        out = jnp.asarray([[0.9], [0.2], [0.6], [0.4]])
        tgt = jnp.asarray([[1.0], [0.0], [0.0], [0.0]])
        correct, count = m.batch(out, tgt)
        assert float(correct) == 3.0 and int(count) == 4
        # keras elementwise semantics for multi-label heads: 4/5 right = 0.8
        out2 = jnp.asarray([[0.9, 0.9, 0.1, 0.1, 0.9]])
        tgt2 = jnp.asarray([[1.0, 1.0, 0.0, 0.0, 0.0]])
        c2, n2 = m.batch(out2, tgt2)
        assert float(c2) == 4.0 and int(n2) == 5

    def test_keras_compile_maps_accuracy_for_bce(self):
        from bigdl_tpu import keras
        from bigdl_tpu.optim.validation import BinaryAccuracy, Top1Accuracy

        m = keras.Sequential(keras.Dense(1, activation="sigmoid",
                                         input_dim=4))
        m.compile(optimizer="sgd", loss="binary_crossentropy",
                  metrics=["accuracy"])
        assert any(isinstance(x, BinaryAccuracy) for x in m.metrics)
        # explicit top1 request is honored even under BCE
        m.compile(optimizer="sgd", loss="binary_crossentropy",
                  metrics=["top1"])
        assert any(isinstance(x, Top1Accuracy) for x in m.metrics)
        m2 = keras.Sequential(keras.Dense(3, input_dim=4))
        m2.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                   metrics=["accuracy"])
        assert any(isinstance(x, Top1Accuracy) for x in m2.metrics)


class TestSyncBnPatchingDepth:
    def test_nested_bns_get_axis_name(self):
        """ParallelOptimizer's sync-BN patch must reach BNs NESTED inside
        Graph blocks (a direct-children scan silently skips them)."""
        from unittest import mock

        from bigdl_tpu.models.resnet import basic_block
        from bigdl_tpu.nn.norm import BatchNormalization
        from bigdl_tpu.optim.optimizer import ParallelOptimizer

        model = nn.Sequential(basic_block(4, 8, 1),
                              nn.GlobalAveragePooling2D(),
                              nn.Linear(8, 2), nn.LogSoftMax())
        nested_bns = [m for m in model.flattened_modules()
                      if isinstance(m, BatchNormalization)]
        assert len(nested_bns) >= 2  # inside the residual Graph
        assert all(m.axis_name is None for m in nested_bns)

        seen = {}

        def fake_optimize(self):
            seen["axis"] = [m.axis_name for m in nested_bns]
            return model

        rs = np.random.RandomState(0)
        from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch

        ds = ArrayDataSet([Sample.from_ndarray(
            rs.rand(4, 4, 4).astype(np.float32), np.int32(0))]
        ).transform(SampleToMiniBatch(1))
        opt = ParallelOptimizer(model, ds, nn.ClassNLLCriterion(),
                                optim_method=SGD(learning_rate=0.1),
                                end_trigger=Trigger.max_iteration(1))
        with mock.patch(
                "bigdl_tpu.optim.optimizer.DistriOptimizer.optimize",
                fake_optimize):
            opt.optimize()
        assert seen["axis"] == ["data"] * len(nested_bns)
        # and restored afterwards
        assert all(m.axis_name is None for m in nested_bns)


class TestAsyncDrainLogging:
    def test_epoch_flush_throughput_is_sane(self, tmp_path):
        """The async drain's burst flush at epoch end must reuse the
        steady-state dt — a sub-millisecond pop gap must not log
        million-records/s throughput to TrainSummary."""
        from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch
        from bigdl_tpu.optim.optimizer import Optimizer
        from bigdl_tpu.utils.summary import TrainSummary

        rs = np.random.RandomState(0)
        xs = rs.rand(32, 6).astype(np.float32)
        ys = (np.arange(32) % 3).astype(np.int32)
        ds = ArrayDataSet([Sample.from_ndarray(x, y)
                           for x, y in zip(xs, ys)]
                          ).transform(SampleToMiniBatch(8))
        model = nn.Sequential(nn.Linear(6, 3), nn.LogSoftMax())
        opt = Optimizer(model, ds, nn.ClassNLLCriterion(),
                        optim_method=SGD(learning_rate=0.1),
                        end_trigger=Trigger.max_epoch(3))
        summ = TrainSummary(str(tmp_path), "drain")
        summ.set_summary_trigger("Throughput", 1)
        opt.set_train_summary(summ)
        opt.optimize()
        vals = [v for _, v in summ.read_scalar("Throughput")]
        assert len(vals) >= 6
        assert all(np.isfinite(v) and 0 < v < 1e7 for v in vals), vals


class TestComputeDtypePolicy:
    def test_bf16_policy_trains_with_f32_masters(self):
        """compute_dtype=bfloat16 runs fwd/bwd in bf16 while params and
        optimizer slots stay fp32 masters (the bench.py policy, now a
        public builder feature)."""
        import jax.numpy as jnp

        ds = make_classification_dataset()
        model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4),
                              nn.LogSoftMax())
        o = optim.LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                                 optim_method=SGD(learning_rate=0.5),
                                 end_trigger=Trigger.max_epoch(5),
                                 compute_dtype=jnp.bfloat16)
        o.optimize()
        # masters stayed fp32
        for leaf in jax.tree_util.tree_leaves(o.params):
            assert leaf.dtype == jnp.float32, leaf.dtype
        for leaf in jax.tree_util.tree_leaves(o.opt_state):
            if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                         jnp.floating):
                assert leaf.dtype == jnp.float32, leaf.dtype
        # and the model still learned the task through bf16 compute
        o.set_validation(Trigger.every_epoch(),
                         make_classification_dataset(seed=1),
                         [Top1Accuracy()])
        acc = o.validate()[0].result()[0]
        assert acc > 0.9, f"accuracy {acc}"

    def test_bf16_policy_keeps_bn_state_f32(self):
        import jax.numpy as jnp

        rs = np.random.RandomState(0)
        xs = rs.rand(64, 6).astype(np.float32)
        ys = (np.arange(64) % 3).astype(np.int32)
        ds = ArrayDataSet([Sample.from_ndarray(x, y) for x, y in zip(xs, ys)]
                          ).transform(SampleToMiniBatch(16))
        model = nn.Sequential(nn.Linear(6, 8), nn.BatchNormalization(8),
                              nn.ReLU(), nn.Linear(8, 3), nn.LogSoftMax())
        o = optim.LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                                 optim_method=SGD(learning_rate=0.1),
                                 end_trigger=Trigger.max_epoch(2),
                                 compute_dtype=jnp.bfloat16)
        o.optimize()
        for leaf in jax.tree_util.tree_leaves(o.model_state):
            if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                         jnp.floating):
                assert leaf.dtype == jnp.float32, leaf.dtype


class TestParallelOptimizerLazyKerasSyncBN:
    def test_bn_inside_keras_adapter_gets_axis_name(self):
        """BNs inside LAZILY-built keras-adapter layers must get sync-BN
        once _init_model has built the inner module (PARITY known-gap,
        closed round 3): trained under ParallelOptimizer on the 8-device
        mesh, the adapter's BatchNormalization uses cross-shard stats."""
        from bigdl_tpu import keras as K
        from bigdl_tpu.core.engine import Engine
        from bigdl_tpu.nn.norm import BatchNormalization
        from bigdl_tpu.optim.optimizer import ParallelOptimizer
        from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch

        Engine.reset()
        Engine.init()
        model = nn.Sequential(
            nn.Linear(6, 8),
            K.layers.BatchNormalization(input_shape=(8,)),  # lazy adapter
            nn.ReLU(), nn.Linear(8, 3), nn.LogSoftMax())
        # before init: the adapter has no inner yet
        adapters = [m for m in model.flattened_modules()
                    if hasattr(m, "_make")]
        assert adapters and all(getattr(a, "inner", None) is None
                                for a in adapters)
        rs = np.random.RandomState(0)
        ds = ArrayDataSet([Sample.from_ndarray(
            rs.rand(6).astype(np.float32), np.int32(i % 3))
            for i in range(32)]).transform(SampleToMiniBatch(16))
        opt = ParallelOptimizer(model, ds, nn.ClassNLLCriterion(),
                                optim_method=SGD(learning_rate=0.1),
                                end_trigger=Trigger.max_iteration(2))
        axes_during = {}
        orig_build = ParallelOptimizer._build_step

        def spy_build(self):
            inner_bns = []
            for a in adapters:
                if a.inner is not None:
                    inner_bns += [m for m in a.inner.flattened_modules()
                                  if isinstance(m, BatchNormalization)]
            axes_during["axes"] = [m.axis_name for m in inner_bns]
            axes_during["n"] = len(inner_bns)
            return orig_build(self)

        from unittest import mock
        with mock.patch.object(ParallelOptimizer, "_build_step", spy_build):
            opt.optimize()
        assert axes_during["n"] >= 1
        assert axes_during["axes"] == ["data"] * axes_during["n"]
        # restored after optimize
        for a in adapters:
            for m in a.inner.flattened_modules():
                if isinstance(m, BatchNormalization):
                    assert m.axis_name is None
