"""TF v1 control-flow import: Enter/Merge/Switch/Exit/NextIteration frames
(+ TensorArrayV3 machinery) -> structured TFWhile lowered to lax.scan (when
the trip count is static — differentiable) or lax.while_loop.

Reference: utils/tf/loaders/ControlFlowOps.scala, nn/tf/ControlOps.scala,
DataFlowOps.scala.  The fixture tests/fixtures/tf_while/drnn.pb is a real
hand-rolled dynamic-rnn graph (tf.while_loop + TensorArray read/write,
frozen with v1 control flow by TF 2.21, see its sibling .npy refs for the
generation inputs/outputs); generating it in-process would require
disabling TF eager for the whole test session, so it is checked in.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.tensorflow import load_tensorflow

# heavyweight tier: differential oracles / trainers / registry sweeps;
# the quick tier is 'pytest -m "not slow"' (README Testing)
pytestmark = pytest.mark.slow


FIX = os.path.join(os.path.dirname(__file__), "fixtures", "tf_while")


def _load_rnn():
    x = np.load(os.path.join(FIX, "drnn_x.npy"))
    ref = np.load(os.path.join(FIX, "drnn_ref.npy"))
    g, gp, gs = load_tensorflow(os.path.join(FIX, "drnn.pb"), ["x"], ["out"],
                                [x.shape])
    return g, gp, gs, x, ref


class TestWhileFrameImport:
    def test_dynamic_rnn_matches_tf(self):
        g, gp, gs, x, ref = _load_rnn()
        y = np.asarray(g.apply(gp, gs, jnp.asarray(x))[0])
        np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)

    def test_counted_loop_lowers_to_scan(self):
        """The cond Less(counter, 5) / counter+=1 pattern must import with
        a static trip count (scan; while_loop would break fine-tuning)."""
        from bigdl_tpu.nn.tf_ops import TFWhile

        g, _, _, _, _ = _load_rnn()
        whiles = [m for m in g.children.values() if isinstance(m, TFWhile)]
        assert len(whiles) == 1
        assert whiles[0].trip_count == 5

    def test_gradients_flow_into_loop_weights(self):
        g, gp, gs, x, _ = _load_rnn()

        def loss(p):
            return jnp.sum(g.apply(p, gs, jnp.asarray(x))[0] ** 2)

        grads = jax.grad(loss)(gp)
        flat = {jax.tree_util.keystr(k): float(jnp.abs(v).sum())
                for k, v in jax.tree_util.tree_flatten_with_path(grads)[0]}
        rnn_w = [v for k, v in flat.items() if "MatMul" in k]
        assert rnn_w and all(v > 0 for v in rnn_w), flat

    def test_session_finetunes_through_loop(self):
        """The reference's Session.train flow (utils/tf/Session.scala:110)
        on a graph WITH a while loop: loss must drop."""
        from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch
        from bigdl_tpu.optim import SGD, Trigger
        from bigdl_tpu.utils.session import Session

        x = np.load(os.path.join(FIX, "drnn_x.npy"))
        rs = np.random.RandomState(0)
        target = rs.randn(2, 5, 4).astype(np.float32) * 0.1
        samples = [Sample.from_ndarray(x[i], target[i]) for i in range(2)]
        ds = ArrayDataSet(samples).transform(SampleToMiniBatch(2))

        sess = Session(os.path.join(FIX, "drnn.pb"), ["x"], [x.shape])
        crit = nn.MSECriterion()
        model = sess.train(["out"], ds, crit,
                           optim_method=SGD(learning_rate=0.5),
                           end_when=Trigger.max_epoch(1))
        before, _ = model.apply(sess.params, sess.state, jnp.asarray(x))
        l0 = float(jnp.mean((np.asarray(before) - target) ** 2))
        sess.train(["out"], ds, crit, optim_method=SGD(learning_rate=0.5),
                   end_when=Trigger.max_epoch(10))
        after, _ = model.apply(sess.params, sess.state, jnp.asarray(x))
        l1 = float(jnp.mean((np.asarray(after) - target) ** 2))
        assert l1 < l0 * 0.7, (l0, l1)


def _nodedef(gd, name, op, inputs=(), **attrs):
    import tf_graph_pb2 as tfp

    nd = gd.node.add()
    nd.name = name
    nd.op = op
    nd.input.extend(inputs)
    for k, v in attrs.items():
        if isinstance(v, bytes):
            nd.attr[k].s = v
        elif isinstance(v, bool):
            nd.attr[k].b = v
        elif isinstance(v, int):
            nd.attr[k].i = v
        elif isinstance(v, np.ndarray):
            from bigdl_tpu.utils.tensorflow import ndarray_to_tensor

            ndarray_to_tensor(v, nd.attr[k].tensor)
    return nd


class TestHandBuiltWhile:
    def test_non_counted_loop_uses_while_loop(self, tmp_path):
        """A data-dependent loop (double v until its sum exceeds 100) has
        no static trip count -> lax.while_loop path, forward-only."""
        import tf_graph_pb2 as tfp

        gd = tfp.GraphDef()
        _nodedef(gd, "x", "Placeholder")
        _nodedef(gd, "limit", "Const",
                 value=np.asarray(100.0, np.float32))
        _nodedef(gd, "two", "Const", value=np.asarray(2.0, np.float32))
        _nodedef(gd, "axis0", "Const", value=np.asarray(0, np.int32))
        _nodedef(gd, "w/Enter", "Enter", ["x"], frame_name=b"w")
        _nodedef(gd, "w/Merge", "Merge", ["w/Enter", "w/NextIteration"])
        _nodedef(gd, "w/Sum", "Sum", ["w/Merge", "axis0"])
        _nodedef(gd, "w/Less", "Less", ["w/Sum", "limit"])
        _nodedef(gd, "w/LoopCond", "LoopCond", ["w/Less"])
        _nodedef(gd, "w/Switch", "Switch", ["w/Merge", "w/LoopCond"])
        _nodedef(gd, "w/Ident", "Identity", ["w/Switch:1"])
        _nodedef(gd, "w/Mul", "Mul", ["w/Ident", "two"])
        _nodedef(gd, "w/NextIteration", "NextIteration", ["w/Mul"])
        _nodedef(gd, "w/Exit", "Exit", ["w/Switch"])
        _nodedef(gd, "out", "Identity", ["w/Exit"])
        pb = str(tmp_path / "loop.pb")
        with open(pb, "wb") as fh:
            fh.write(gd.SerializeToString())

        g, gp, gs = load_tensorflow(pb, ["x"], ["out"], [(4,)])
        from bigdl_tpu.nn.tf_ops import TFWhile

        whiles = [m for m in g.children.values() if isinstance(m, TFWhile)]
        assert len(whiles) == 1 and whiles[0].trip_count is None

        x = np.asarray([1.0, 2.0, 3.0, 4.0], np.float32)
        y = np.asarray(g.apply(gp, gs, jnp.asarray(x))[0])
        want = x.copy()
        while want.sum() < 100.0:
            want = want * 2.0
        np.testing.assert_allclose(y, want, rtol=1e-6)


class TestStandaloneCond:
    def _build(self, tmp_path):
        import tf_graph_pb2 as tfp

        gd = tfp.GraphDef()
        _nodedef(gd, "x", "Placeholder")
        _nodedef(gd, "thr", "Const", value=np.asarray(10.0, np.float32))
        _nodedef(gd, "ten", "Const", value=np.asarray(10.0, np.float32))
        _nodedef(gd, "two", "Const", value=np.asarray(2.0, np.float32))
        _nodedef(gd, "axis0", "Const", value=np.asarray(0, np.int32))
        _nodedef(gd, "s", "Sum", ["x", "axis0"])
        _nodedef(gd, "pred", "Less", ["s", "thr"])
        _nodedef(gd, "sw", "Switch", ["x", "pred"])
        _nodedef(gd, "tbr", "Mul", ["sw:1", "two"])      # pred true: x*2
        _nodedef(gd, "fbr", "Add", ["sw", "ten"])        # pred false: x+10
        _nodedef(gd, "mg", "Merge", ["fbr", "tbr"])
        _nodedef(gd, "out", "Identity", ["mg"])
        pb = str(tmp_path / "cond.pb")
        with open(pb, "wb") as fh:
            fh.write(gd.SerializeToString())
        return load_tensorflow(pb, ["x"], ["out"], [(4,)])

    def test_both_predicate_outcomes(self, tmp_path):
        g, gp, gs = self._build(tmp_path)
        small = np.asarray([1.0, 1.0, 1.0, 1.0], np.float32)   # sum < 10
        big = np.asarray([5.0, 5.0, 5.0, 5.0], np.float32)     # sum >= 10
        y_small = np.asarray(g.apply(gp, gs, jnp.asarray(small))[0])
        y_big = np.asarray(g.apply(gp, gs, jnp.asarray(big))[0])
        np.testing.assert_allclose(y_small, small * 2.0)
        np.testing.assert_allclose(y_big, big + 10.0)

    def test_cond_is_differentiable(self, tmp_path):
        g, gp, gs = self._build(tmp_path)

        def f(x):
            return jnp.sum(g.apply(gp, gs, x)[0])

        grad_small = np.asarray(jax.grad(f)(jnp.asarray(
            [1.0, 1.0, 1.0, 1.0], dtype=jnp.float32)))
        grad_big = np.asarray(jax.grad(f)(jnp.asarray(
            [5.0, 5.0, 5.0, 5.0], dtype=jnp.float32)))
        np.testing.assert_allclose(grad_small, np.full(4, 2.0))
        np.testing.assert_allclose(grad_big, np.full(4, 1.0))

    def test_lowered_to_structured_lax_cond(self, tmp_path):
        """The importer must produce a TFCond (lax.cond — only the taken
        branch runs), not the both-branches MergeSelect fallback."""
        from bigdl_tpu.nn.tf_ops import TFCond

        g, _, _ = self._build(tmp_path)
        conds = [m for m in g.children.values() if isinstance(m, TFCond)]
        assert len(conds) == 1

    def test_guard_cond_gradient_has_no_nan(self, tmp_path):
        """Guard-style cond(x >= 0 ? sqrt(x) : -x): with both-branch
        evaluation the untaken sqrt branch's reverse-mode derivative at
        x < 0 is NaN and 0 * NaN leaks; lax.cond differentiates only the
        taken branch."""
        import tf_graph_pb2 as tfp

        gd = tfp.GraphDef()
        _nodedef(gd, "x", "Placeholder")
        _nodedef(gd, "zero", "Const", value=np.asarray(0.0, np.float32))
        _nodedef(gd, "axis0", "Const", value=np.asarray(0, np.int32))
        _nodedef(gd, "s", "Sum", ["x", "axis0"])
        _nodedef(gd, "pred", "GreaterEqual", ["s", "zero"])
        _nodedef(gd, "sw", "Switch", ["x", "pred"])
        _nodedef(gd, "tbr", "Sqrt", ["sw:1"])
        _nodedef(gd, "fbr", "Neg", ["sw"])
        _nodedef(gd, "mg", "Merge", ["fbr", "tbr"])
        _nodedef(gd, "out", "Identity", ["mg"])
        pb = str(tmp_path / "guard.pb")
        with open(pb, "wb") as fh:
            fh.write(gd.SerializeToString())
        g, gp, gs = load_tensorflow(pb, ["x"], ["out"], [(4,)])

        def f(x):
            return jnp.sum(g.apply(gp, gs, x)[0])

        neg = jnp.asarray([-1.0, -2.0, -3.0, -4.0], dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(f(neg)), 10.0, rtol=1e-6)
        grad = np.asarray(jax.grad(f)(neg))
        assert np.all(np.isfinite(grad)), grad
        np.testing.assert_allclose(grad, np.full(4, -1.0))

    def test_crosslinked_cond_now_lowers_structured(self, tmp_path):
        """The FORMERLY-non-separable fixture (r4 verdict item 4): a node
        (`mix`) consumes BOTH Switch sides.  Round 5 splits the region —
        the cross-linked node converts on the eager SwitchGate path while
        the Merge still lowers to lax.cond, so the guard branches
        (sqrt/neg) execute ONE side only.  Asserted structurally: TFCond
        present, MergeSelect absent, and the jaxpr contains a cond
        primitive whose branches hold the sqrt (taken-branch-only
        execution), not an unconditional inline sqrt."""
        import tf_graph_pb2 as tfp

        gd = tfp.GraphDef()
        _nodedef(gd, "x", "Placeholder")
        _nodedef(gd, "zero", "Const", value=np.asarray(0.0, np.float32))
        _nodedef(gd, "axis0", "Const", value=np.asarray(0, np.int32))
        _nodedef(gd, "s", "Sum", ["x", "axis0"])
        _nodedef(gd, "pred", "GreaterEqual", ["s", "zero"])
        _nodedef(gd, "sw", "Switch", ["x", "pred"])
        # `mix` consumes BOTH Switch sides (always-dead in real TF; the
        # framework's defined extension is the SwitchGate clamp value)
        _nodedef(gd, "mix", "Mul", ["sw", "sw:1"])
        _nodedef(gd, "tbr", "Sqrt", ["sw:1"])
        _nodedef(gd, "fbr", "Neg", ["sw"])
        _nodedef(gd, "mg", "Merge", ["fbr", "tbr"])
        _nodedef(gd, "out", "Identity", ["mg"])
        _nodedef(gd, "out2", "Identity", ["mix"])
        pb = str(tmp_path / "guard_fallback.pb")
        with open(pb, "wb") as fh:
            fh.write(gd.SerializeToString())
        g, gp, gs = load_tensorflow(pb, ["x"], ["out", "out2"], [(4,)])

        from bigdl_tpu.nn.tf_ops import MergeSelect, TFCond

        assert any(isinstance(m, TFCond) for m in g.children.values())
        assert not any(isinstance(m, MergeSelect) for m in g.children.values())
        # jaxpr-level proof of one-branch execution: sqrt appears inside
        # a cond branch, not in the main trace
        jaxpr = jax.make_jaxpr(
            lambda x: g.apply(gp, gs, x)[0])(jnp.ones(4))
        main_prims = [e.primitive.name for e in jaxpr.jaxpr.eqns]
        assert "cond" in main_prims
        assert "sqrt" not in main_prims  # only inside the cond branch
        cond_eqn = next(e for e in jaxpr.jaxpr.eqns
                        if e.primitive.name == "cond")
        branch_prims = {p.name for br in cond_eqn.params["branches"]
                        for p in [eq.primitive for eq in br.jaxpr.eqns]}
        assert "sqrt" in branch_prims

        def f(x):
            return jnp.sum(g.apply(gp, gs, x)[0][1])

        # pred FALSE: out = -x; the sqrt branch runs on gated ones
        neg = jnp.asarray([-1.0, -2.0, -3.0, -4.0], dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(f(neg)), 10.0, rtol=1e-6)
        grad = np.asarray(jax.grad(f)(neg))
        assert np.all(np.isfinite(grad)), grad
        np.testing.assert_allclose(grad, np.full(4, -1.0))
        # pred TRUE: out = sqrt(x), grad = 0.5/sqrt(x)
        pos = jnp.asarray([1.0, 4.0, 9.0, 16.0], dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(f(pos)),
                                   np.sum(np.sqrt(np.asarray(pos))),
                                   rtol=1e-6)
        grad_pos = np.asarray(jax.grad(f)(pos))
        np.testing.assert_allclose(grad_pos,
                                   0.5 / np.sqrt(np.asarray(pos)),
                                   rtol=1e-5)

    def test_dual_input_merge_pins_eager_fallback(self, tmp_path):
        """The PRECISE remaining fallback class (r4 verdict item 4): a
        Merge fed by a cross-linked (both-sides) producer.  No port
        assignment is TF-consistent (the producer is always-dead in real
        TF), so the region must stay on the eager SwitchGate/MergeSelect
        path — pinned here so the class is documented by a test."""
        import tf_graph_pb2 as tfp

        gd = tfp.GraphDef()
        _nodedef(gd, "x", "Placeholder")
        _nodedef(gd, "zero", "Const", value=np.asarray(0.0, np.float32))
        _nodedef(gd, "axis0", "Const", value=np.asarray(0, np.int32))
        _nodedef(gd, "s", "Sum", ["x", "axis0"])
        _nodedef(gd, "pred", "GreaterEqual", ["s", "zero"])
        _nodedef(gd, "sw", "Switch", ["x", "pred"])
        _nodedef(gd, "mix", "Mul", ["sw", "sw:1"])  # dual-side producer
        _nodedef(gd, "tbr", "Sqrt", ["sw:1"])
        # the Merge itself consumes the dual node -> no side mapping
        _nodedef(gd, "mg", "Merge", ["mix", "tbr"])
        _nodedef(gd, "out", "Identity", ["mg"])
        pb = str(tmp_path / "dual_merge.pb")
        with open(pb, "wb") as fh:
            fh.write(gd.SerializeToString())
        g, gp, gs = load_tensorflow(pb, ["x"], ["out"], [(4,)])

        from bigdl_tpu.nn.tf_ops import MergeSelect, TFCond

        assert not any(isinstance(m, TFCond) for m in g.children.values())
        assert any(isinstance(m, MergeSelect) for m in g.children.values())
        # and the eager lowering still evaluates finitely both ways
        for vec in ([1.0, 4.0, 9.0, 16.0], [-1.0, -2.0, -3.0, -4.0]):
            val = g.apply(gp, gs, jnp.asarray(vec, jnp.float32))[0]
            assert np.all(np.isfinite(np.asarray(val)))

    def test_dual_node_reading_branch_member_falls_back(self, tmp_path):
        """Regression (r5 review): a cross-linked node consuming
        SINGLE-side branch members (mix = tbr*fbr) must push the whole
        region onto the eager path — structuring it would trap tbr/fbr
        inside the lax.cond branches while mix needs them eagerly."""
        import tf_graph_pb2 as tfp

        gd = tfp.GraphDef()
        _nodedef(gd, "x", "Placeholder")
        _nodedef(gd, "zero", "Const", value=np.asarray(0.0, np.float32))
        _nodedef(gd, "axis0", "Const", value=np.asarray(0, np.int32))
        _nodedef(gd, "s", "Sum", ["x", "axis0"])
        _nodedef(gd, "pred", "GreaterEqual", ["s", "zero"])
        _nodedef(gd, "sw", "Switch", ["x", "pred"])
        _nodedef(gd, "tbr", "Sqrt", ["sw:1"])
        _nodedef(gd, "fbr", "Neg", ["sw"])
        _nodedef(gd, "mix", "Mul", ["tbr", "fbr"])  # dual via pure members
        _nodedef(gd, "mg", "Merge", ["fbr", "tbr"])
        _nodedef(gd, "out", "Add", ["mg", "mix"])
        pb = str(tmp_path / "dual_reads_member.pb")
        with open(pb, "wb") as fh:
            fh.write(gd.SerializeToString())
        g, gp, gs = load_tensorflow(pb, ["x"], ["out"], [(4,)])

        from bigdl_tpu.nn.tf_ops import MergeSelect, TFCond

        assert not any(isinstance(m, TFCond) for m in g.children.values())
        assert any(isinstance(m, MergeSelect) for m in g.children.values())
        for vec in ([1.0, 4.0, 9.0, 16.0], [-1.0, -2.0, -3.0, -4.0]):
            val = g.apply(gp, gs, jnp.asarray(vec, jnp.float32))[0]
            assert np.all(np.isfinite(np.asarray(val)))

    def test_shared_predicate_multi_output_cond(self, tmp_path):
        """Two Switches + two Merges on one predicate import as a single
        multi-output TFCond (region grouping by predicate)."""
        import tf_graph_pb2 as tfp

        gd = tfp.GraphDef()
        _nodedef(gd, "x", "Placeholder")
        _nodedef(gd, "y", "Placeholder")
        _nodedef(gd, "thr", "Const", value=np.asarray(10.0, np.float32))
        _nodedef(gd, "two", "Const", value=np.asarray(2.0, np.float32))
        _nodedef(gd, "axis0", "Const", value=np.asarray(0, np.int32))
        _nodedef(gd, "s", "Sum", ["x", "axis0"])
        _nodedef(gd, "pred", "Less", ["s", "thr"])
        _nodedef(gd, "swx", "Switch", ["x", "pred"])
        _nodedef(gd, "swy", "Switch", ["y", "pred"])
        _nodedef(gd, "tx", "Mul", ["swx:1", "two"])       # true: x*2, y+x*2
        _nodedef(gd, "ty", "Add", ["swy:1", "tx"])
        _nodedef(gd, "fx", "Neg", ["swx"])                # false: -x, y*2
        _nodedef(gd, "fy", "Mul", ["swy", "two"])
        _nodedef(gd, "mgx", "Merge", ["fx", "tx"])
        _nodedef(gd, "mgy", "Merge", ["fy", "ty"])
        _nodedef(gd, "outx", "Identity", ["mgx"])
        _nodedef(gd, "outy", "Identity", ["mgy"])
        pb = str(tmp_path / "multi.pb")
        with open(pb, "wb") as fh:
            fh.write(gd.SerializeToString())
        g, gp, gs = load_tensorflow(pb, ["x", "y"], ["outx", "outy"],
                                    [(4,), (4,)])
        from bigdl_tpu.core.table import Table
        from bigdl_tpu.nn.tf_ops import TFCond

        assert sum(isinstance(m, TFCond) for m in g.children.values()) == 1
        small = np.full(4, 1.0, np.float32)
        yv = np.full(4, 3.0, np.float32)
        out = g.apply(gp, gs, Table(jnp.asarray(small), jnp.asarray(yv)))[0]
        ox, oy = (np.asarray(v) for v in out)
        np.testing.assert_allclose(ox, small * 2.0)
        np.testing.assert_allclose(oy, yv + small * 2.0)
        big = np.full(4, 5.0, np.float32)
        out = g.apply(gp, gs, Table(jnp.asarray(big), jnp.asarray(yv)))[0]
        ox, oy = (np.asarray(v) for v in out)
        np.testing.assert_allclose(ox, -big)
        np.testing.assert_allclose(oy, yv * 2.0)

    def test_cascaded_conds_on_shared_predicate(self, tmp_path):
        """Two SEQUENTIAL conds guarded by the same predicate (reused
        is_training-style flag): the second cond's data input depends on
        the first cond's Merge.  Region detection must split them into two
        components or the second region's readiness waits on its own
        group's Merge forever."""
        import tf_graph_pb2 as tfp

        gd = tfp.GraphDef()
        _nodedef(gd, "x", "Placeholder")
        _nodedef(gd, "thr", "Const", value=np.asarray(10.0, np.float32))
        _nodedef(gd, "two", "Const", value=np.asarray(2.0, np.float32))
        _nodedef(gd, "ten", "Const", value=np.asarray(10.0, np.float32))
        _nodedef(gd, "axis0", "Const", value=np.asarray(0, np.int32))
        _nodedef(gd, "s", "Sum", ["x", "axis0"])
        _nodedef(gd, "pred", "Less", ["s", "thr"])
        # cond 1: x*2 | x+10
        _nodedef(gd, "sw1", "Switch", ["x", "pred"])
        _nodedef(gd, "t1", "Mul", ["sw1:1", "two"])
        _nodedef(gd, "f1", "Add", ["sw1", "ten"])
        _nodedef(gd, "mg1", "Merge", ["f1", "t1"])
        # intermediate layer between the two conds
        _nodedef(gd, "mid", "Add", ["mg1", "two"])
        # cond 2 (same predicate): mid+10 | mid*2
        _nodedef(gd, "sw2", "Switch", ["mid", "pred"])
        _nodedef(gd, "t2", "Add", ["sw2:1", "ten"])
        _nodedef(gd, "f2", "Mul", ["sw2", "two"])
        _nodedef(gd, "mg2", "Merge", ["f2", "t2"])
        _nodedef(gd, "out", "Identity", ["mg2"])
        pb = str(tmp_path / "cascade.pb")
        with open(pb, "wb") as fh:
            fh.write(gd.SerializeToString())
        g, gp, gs = load_tensorflow(pb, ["x"], ["out"], [(4,)])
        from bigdl_tpu.nn.tf_ops import TFCond

        assert sum(isinstance(m, TFCond) for m in g.children.values()) == 2
        small = np.full(4, 1.0, np.float32)   # sum=4 < 10: true branches
        y = np.asarray(g.apply(gp, gs, jnp.asarray(small))[0])
        np.testing.assert_allclose(y, (small * 2.0 + 2.0) + 10.0)
        big = np.full(4, 5.0, np.float32)     # sum=20 >= 10: false branches
        y = np.asarray(g.apply(gp, gs, jnp.asarray(big))[0])
        np.testing.assert_allclose(y, (big + 10.0 + 2.0) * 2.0)


class TestNestedWhile:
    def test_nested_counted_loops(self, tmp_path):
        """Inner counted loop (double v twice) inside an outer counted
        loop (3 iterations): v * 2^(2*3).  The inner frame converts inside
        the outer body sub-import."""
        import tf_graph_pb2 as tfp

        gd = tfp.GraphDef()
        _nodedef(gd, "x", "Placeholder")
        _nodedef(gd, "c0", "Const", value=np.asarray(0, np.int32))
        _nodedef(gd, "c3", "Const", value=np.asarray(3, np.int32))
        _nodedef(gd, "c2i", "Const", value=np.asarray(2, np.int32))
        _nodedef(gd, "one", "Const", value=np.asarray(1, np.int32))
        _nodedef(gd, "two", "Const", value=np.asarray(2.0, np.float32))
        # outer frame "o": vars (t, v)
        _nodedef(gd, "o/Enter_t", "Enter", ["c0"], frame_name=b"o")
        _nodedef(gd, "o/Enter_v", "Enter", ["x"], frame_name=b"o")
        _nodedef(gd, "o/Merge_t", "Merge", ["o/Enter_t", "o/NextIteration_t"])
        _nodedef(gd, "o/Merge_v", "Merge", ["o/Enter_v", "o/NextIteration_v"])
        _nodedef(gd, "o/Less", "Less", ["o/Merge_t", "c3"])
        _nodedef(gd, "o/LoopCond", "LoopCond", ["o/Less"])
        _nodedef(gd, "o/Switch_t", "Switch", ["o/Merge_t", "o/LoopCond"])
        _nodedef(gd, "o/Switch_v", "Switch", ["o/Merge_v", "o/LoopCond"])
        _nodedef(gd, "o/Ident_t", "Identity", ["o/Switch_t:1"])
        _nodedef(gd, "o/Ident_v", "Identity", ["o/Switch_v:1"])
        _nodedef(gd, "o/add_t", "Add", ["o/Ident_t", "one"])
        # inner frame "i": vars (s, w); w enters from the outer body
        _nodedef(gd, "i/Enter_s", "Enter", ["c0"], frame_name=b"i")
        _nodedef(gd, "i/Enter_w", "Enter", ["o/Ident_v"], frame_name=b"i")
        _nodedef(gd, "i/Merge_s", "Merge", ["i/Enter_s", "i/NextIteration_s"])
        _nodedef(gd, "i/Merge_w", "Merge", ["i/Enter_w", "i/NextIteration_w"])
        _nodedef(gd, "i/Less", "Less", ["i/Merge_s", "c2i"])
        _nodedef(gd, "i/LoopCond", "LoopCond", ["i/Less"])
        _nodedef(gd, "i/Switch_s", "Switch", ["i/Merge_s", "i/LoopCond"])
        _nodedef(gd, "i/Switch_w", "Switch", ["i/Merge_w", "i/LoopCond"])
        _nodedef(gd, "i/Ident_s", "Identity", ["i/Switch_s:1"])
        _nodedef(gd, "i/Ident_w", "Identity", ["i/Switch_w:1"])
        _nodedef(gd, "i/add_s", "Add", ["i/Ident_s", "one"])
        _nodedef(gd, "i/mul_w", "Mul", ["i/Ident_w", "two"])
        _nodedef(gd, "i/NextIteration_s", "NextIteration", ["i/add_s"])
        _nodedef(gd, "i/NextIteration_w", "NextIteration", ["i/mul_w"])
        _nodedef(gd, "i/Exit_s", "Exit", ["i/Switch_s"])
        _nodedef(gd, "i/Exit_w", "Exit", ["i/Switch_w"])
        # close the outer loop
        _nodedef(gd, "o/NextIteration_t", "NextIteration", ["o/add_t"])
        _nodedef(gd, "o/NextIteration_v", "NextIteration", ["i/Exit_w"])
        _nodedef(gd, "o/Exit_t", "Exit", ["o/Switch_t"])
        _nodedef(gd, "o/Exit_v", "Exit", ["o/Switch_v"])
        _nodedef(gd, "out", "Identity", ["o/Exit_v"])
        pb = str(tmp_path / "nested.pb")
        with open(pb, "wb") as fh:
            fh.write(gd.SerializeToString())

        g, gp, gs = load_tensorflow(pb, ["x"], ["out"], [(4,)])
        x = np.asarray([1.0, 2.0, 3.0, 4.0], np.float32)
        y = np.asarray(g.apply(gp, gs, jnp.asarray(x))[0])
        np.testing.assert_allclose(y, x * 64.0, rtol=1e-6)


class TestCondInsideWhile:
    def test_counted_loop_with_body_cond(self, tmp_path):
        """A tf.cond INSIDE a while body (its Switch/Merge are frame
        members but not loop skeleton) imports: loop-var Merges are
        Merge(Enter, NextIteration); the body cond converts via the
        sub-import (structured TFCond when cleanly separable).
        v' = sum(v) < 10 ? v*2 : v+1,
        4 iterations from [1, 1] -> [2,2] -> [4,4] -> [8,8] -> [9,9]."""
        import tf_graph_pb2 as tfp

        gd = tfp.GraphDef()
        _nodedef(gd, "x", "Placeholder")
        _nodedef(gd, "c0", "Const", value=np.asarray(0, np.int32))
        _nodedef(gd, "c4", "Const", value=np.asarray(4, np.int32))
        _nodedef(gd, "one_i", "Const", value=np.asarray(1, np.int32))
        _nodedef(gd, "one_f", "Const", value=np.asarray(1.0, np.float32))
        _nodedef(gd, "two", "Const", value=np.asarray(2.0, np.float32))
        _nodedef(gd, "thr", "Const", value=np.asarray(10.0, np.float32))
        _nodedef(gd, "axis0", "Const", value=np.asarray(0, np.int32))
        # frame "w": vars (t counter, v value)
        _nodedef(gd, "w/Enter_t", "Enter", ["c0"], frame_name=b"w")
        _nodedef(gd, "w/Enter_v", "Enter", ["x"], frame_name=b"w")
        _nodedef(gd, "w/Merge_t", "Merge", ["w/Enter_t", "w/NextIteration_t"])
        _nodedef(gd, "w/Merge_v", "Merge", ["w/Enter_v", "w/NextIteration_v"])
        _nodedef(gd, "w/Less", "Less", ["w/Merge_t", "c4"])
        _nodedef(gd, "w/LoopCond", "LoopCond", ["w/Less"])
        _nodedef(gd, "w/Switch_t", "Switch", ["w/Merge_t", "w/LoopCond"])
        _nodedef(gd, "w/Switch_v", "Switch", ["w/Merge_v", "w/LoopCond"])
        _nodedef(gd, "w/Ident_t", "Identity", ["w/Switch_t:1"])
        _nodedef(gd, "w/Ident_v", "Identity", ["w/Switch_v:1"])
        _nodedef(gd, "w/add_t", "Add", ["w/Ident_t", "one_i"])
        # body cond: pred = sum(v) < thr
        _nodedef(gd, "w/sum", "Sum", ["w/Ident_v", "axis0"])
        _nodedef(gd, "w/pred", "Less", ["w/sum", "thr"])
        _nodedef(gd, "w/csw", "Switch", ["w/Ident_v", "w/pred"])
        _nodedef(gd, "w/tbr", "Mul", ["w/csw:1", "two"])
        _nodedef(gd, "w/fbr", "Add", ["w/csw", "one_f"])
        _nodedef(gd, "w/cmg", "Merge", ["w/fbr", "w/tbr"])
        _nodedef(gd, "w/NextIteration_t", "NextIteration", ["w/add_t"])
        _nodedef(gd, "w/NextIteration_v", "NextIteration", ["w/cmg"])
        _nodedef(gd, "w/Exit_t", "Exit", ["w/Switch_t"])
        _nodedef(gd, "w/Exit_v", "Exit", ["w/Switch_v"])
        _nodedef(gd, "out", "Identity", ["w/Exit_v"])
        pb = str(tmp_path / "cond_in_while.pb")
        with open(pb, "wb") as fh:
            fh.write(gd.SerializeToString())
        g, gp, gs = load_tensorflow(pb, ["x"], ["out"], [(2,)])
        from bigdl_tpu.nn.tf_ops import TFCond, TFWhile

        wh = [m for m in g.children.values() if isinstance(m, TFWhile)][0]
        assert any(isinstance(m, TFCond)
                   for m in wh.body_graph.flattened_modules()), \
            "body cond should lower to structured TFCond/lax.cond"
        x = np.asarray([1.0, 1.0], np.float32)
        y = np.asarray(g.apply(gp, gs, jnp.asarray(x))[0])
        np.testing.assert_allclose(y, [9.0, 9.0])
        # differentiable through scan(cond): d/dx (x * 2^3) = 8 on the
        # taken-branch path
        gr = jax.grad(lambda v: jnp.sum(g.apply(gp, gs, v)[0]))(
            jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(gr), [8.0, 8.0])
