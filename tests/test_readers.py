"""Disaggregated reader pool (dataset/readers.py) — ISSUE 9.

Pins the load-bearing properties of the multi-process input plane:
  * strict-order delivery: batch k's CONTENT is a pure function of
    (work, k), so procs=1 and procs=4 epoch sequences are bitwise-equal
    (the reorder stage, not a static worker:shard map, owns determinism);
  * resume: `start_index` makes workers skip cheap items, and the pooled
    kill->resume trajectory stays bitwise-equal to the uninterrupted run
    (chaos lane);
  * failure: a worker that dies — exception or SIGKILL, even with the
    queue full — surfaces as ReaderWorkerError from the consumer within
    a bounded time instead of deadlocking DeviceFeed shutdown;
  * lifecycle: close() reaps every child (conftest's process-leak guard
    backstops all tests here), and the feed-off InlineFeed path closes
    through the same way;
  * autoscale: the stall EMA grows/shrinks the worker count with
    hysteresis and exports the `feed/reader_procs` gauge.
"""

import os
import time

import numpy as np
import pytest

import jax

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.core.random import RandomGenerator
from bigdl_tpu.dataset import (ArrayDataSet, MiniBatch, Sample,
                               SampleToMiniBatch)
from bigdl_tpu.dataset.feed import DeviceFeed, InlineFeed
from bigdl_tpu.dataset.readers import (ChunkWork, ReaderPool,
                                       ReaderWorkerError, make_reader_source,
                                       reader_work_for)
from bigdl_tpu.dataset.tfrecord import ParsedExampleDataSet, TFRecordWriter
from bigdl_tpu.dataset.transformer import FnTransformer, Transformer
from bigdl_tpu.nn.tf_ops import build_example_proto
from bigdl_tpu.optim import SGD, Trigger


def _ident_chunks(n=23, chunk=4):
    return ChunkWork(list(range(n)), chunk,
                     lambda c: np.asarray(c, np.int64))


def _class_ds(n=96, dim=6, classes=3, batch=16, seed=0):
    centers = np.random.RandomState(99).randn(classes, dim).astype(np.float32) * 3
    rs = np.random.RandomState(seed)
    samples = [Sample.from_ndarray(
        centers[i % classes] + rs.randn(dim).astype(np.float32) * 0.3,
        np.int32(i % classes)) for i in range(n)]
    return ArrayDataSet(samples).transform(SampleToMiniBatch(batch))


def _mlp(dim=6, classes=3):
    return nn.Sequential(nn.Linear(dim, 16), nn.ReLU(),
                         nn.Linear(16, classes), nn.LogSoftMax())


def _write_shards(tmp_path, n_shards=3, per_shard=40, dim=4):
    rs = np.random.RandomState(0)
    paths = []
    for s in range(n_shards):
        p = str(tmp_path / f"shard{s}.tfrecord")
        with TFRecordWriter(p) as w:
            for i in range(per_shard):
                w.write(build_example_proto(
                    {"x": rs.randn(dim).astype(np.float32),
                     "y": np.asarray([s * per_shard + i], np.int64)}))
        paths.append(p)
    return paths


def _parsed_ds(paths, batch=8, dim=4):
    # skip_corrupt=True routes through the sequential python framing
    # reader on the inline path too, so pool-vs-inline is apples to apples
    return ParsedExampleDataSet(paths, batch_size=batch,
                                dense_keys=["x", "y"],
                                dense_shapes=[(dim,), ()], label_key="y",
                                skip_corrupt=True)


def _batches(it):
    return [(np.asarray(b.get_input()), np.asarray(b.get_target()))
            for b in it]


def _assert_batches_equal(a, b):
    assert len(a) == len(b)
    for i, ((xa, ya), (xb, yb)) in enumerate(zip(a, b)):
        assert xa.dtype == xb.dtype and ya.dtype == yb.dtype
        np.testing.assert_array_equal(xa, xb, err_msg=f"batch {i} input")
        np.testing.assert_array_equal(ya, yb, err_msg=f"batch {i} target")


# ----------------------------------------------------------------------
# ChunkWork / pool unit behaviour
# ----------------------------------------------------------------------

class TestChunkWork:
    def test_len_and_tail(self):
        assert len(ChunkWork(list(range(10)), 4, None)) == 2
        assert len(ChunkWork(list(range(10)), 4, None, keep_tail=True)) == 3
        assert len(ChunkWork(list(range(8)), 4, None, keep_tail=True)) == 2

    def test_item_stream_slices(self):
        w = ChunkWork(list(range(10)), 3, None, keep_tail=True)
        assert list(w.item_stream(0)) == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
        assert list(w.item_stream(2)) == [[6, 7, 8], [9]]


class TestReaderPoolUnit:
    def test_strict_order_multi_proc(self):
        with ReaderPool(_ident_chunks(), procs=2) as pool:
            got = list(pool)
        assert len(got) == 5
        for k, g in enumerate(got):
            np.testing.assert_array_equal(
                g, np.asarray(list(range(23))[k * 4:(k + 1) * 4], np.int64))

    def test_start_index_resume_skip(self):
        with ReaderPool(_ident_chunks(), procs=2, start_index=3) as pool:
            got = list(pool)
        assert [list(g) for g in got] == [[12, 13, 14, 15], [16, 17, 18, 19]]

    def test_worker_exception_surfaces_with_traceback(self):
        def boom(chunk):
            raise ValueError("kaput record")

        with ReaderPool(ChunkWork(list(range(8)), 2, boom), procs=2) as pool:
            with pytest.raises(ReaderWorkerError, match="kaput record"):
                next(iter(pool))

    def test_sigkilled_worker_surfaces_not_hangs(self):
        def slow(chunk):
            time.sleep(0.005)
            return np.asarray(chunk)

        pool = ReaderPool(ChunkWork(list(range(4000)), 2, slow), procs=2)
        it = iter(pool)
        next(it)
        for p in list(pool._workers.values()):
            p.kill()
        t0 = time.monotonic()
        with pytest.raises(ReaderWorkerError, match="died"):
            for _ in range(5000):
                next(it)
        assert time.monotonic() - t0 < 10.0
        pool.close()

    def test_close_mid_stream_is_bounded_and_idempotent(self):
        pool = ReaderPool(_ident_chunks(n=4000, chunk=2), procs=3)
        it = iter(pool)
        for _ in range(3):
            next(it)
        t0 = time.monotonic()
        pool.close()
        pool.close()
        assert time.monotonic() - t0 < 8.0
        with pytest.raises(StopIteration):
            next(it)

    def test_window_bounds_claims(self):
        # claim ceiling = served + window: with the consumer stopped,
        # workers cannot run away past the window
        pool = ReaderPool(_ident_chunks(n=4000, chunk=2), procs=2, window=4)
        try:
            time.sleep(0.5)
            assert int(pool._claim.value) <= 4
        finally:
            pool.close()


# ----------------------------------------------------------------------
# dataset adapters: deterministic resharding
# ----------------------------------------------------------------------

class TestDatasetAdapters:
    def test_array_dataset_pool_matches_inline(self):
        RandomGenerator.set_seed(1234)
        inline = _batches(_class_ds().data(train=True))
        RandomGenerator.set_seed(1234)
        src = make_reader_source(_class_ds(), train=True, procs=3)
        assert src is not None
        with src:
            pooled = _batches(src)
        _assert_batches_equal(inline, pooled)

    def test_array_dataset_procs_1_vs_4_bitwise(self):
        def epoch(procs):
            RandomGenerator.set_seed(7)
            ds = _class_ds()
            out = []
            for _ in range(2):  # two epochs: the shuffle replay advances
                with make_reader_source(ds, train=True, procs=procs) as src:
                    out.append(_batches(src))
            return out

        a, b = epoch(1), epoch(4)
        for ea, eb in zip(a, b):
            _assert_batches_equal(ea, eb)
        # and the two epochs genuinely reshuffled
        assert not np.array_equal(a[0][0][0], a[1][0][0])

    def test_transform_chain_applies_in_workers(self):
        RandomGenerator.set_seed(5)
        ds = (ArrayDataSet([Sample.from_ndarray(
            np.full((3,), i, np.float32), np.int32(i)) for i in range(32)])
            .transform(FnTransformer(lambda s: Sample(s.feature * 2.0,
                                                      s.label)))
            .transform(SampleToMiniBatch(8)))
        with make_reader_source(ds, train=False, procs=2) as src:
            got = _batches(src)
        assert len(got) == 4
        # FnTransformer ran: features are doubled
        np.testing.assert_array_equal(got[0][0][0], np.full((3,), 0.0))
        np.testing.assert_array_equal(got[1][0][0], np.full((3,), 16.0))

    def test_opaque_transformer_falls_back(self):
        class Stateful(Transformer):
            def __call__(self, it):
                for i, s in enumerate(it):
                    if i % 2 == 0:  # filtering: not chunk-alignable
                        yield s

        ds = (ArrayDataSet([Sample.from_ndarray(np.zeros(2, np.float32),
                                                np.int32(0))] * 16)
              .transform(Stateful())
              .transform(SampleToMiniBatch(4)))
        assert reader_work_for(ds, train=False) is None
        assert make_reader_source(ds, train=False, procs=2) is None

    def test_zero_procs_means_no_pool(self):
        assert make_reader_source(_class_ds(), train=True, procs=0) is None


class TestParsedExampleReaders:
    def test_pool_matches_inline_and_procs_reshard(self, tmp_path):
        paths = _write_shards(tmp_path)
        RandomGenerator.set_seed(42)
        inline = _batches(_parsed_ds(paths).data(train=True))

        def pooled_epoch(procs):
            RandomGenerator.set_seed(42)
            ds = _parsed_ds(paths)
            with ReaderPool(ds.reader_work(train=True), procs=procs,
                            on_corrupt=ds._count_corrupt) as pool:
                return _batches(pool)

        one, four = pooled_epoch(1), pooled_epoch(4)
        _assert_batches_equal(inline, one)
        _assert_batches_equal(one, four)

    def test_corrupt_record_counted_once_across_workers(self, tmp_path):
        import struct

        paths = _write_shards(tmp_path, n_shards=2, per_shard=24)
        # flip one payload byte of shard0's first record: framing stays
        # intact, data crc mismatches, skip_corrupt resyncs past it
        with open(paths[0], "r+b") as fh:
            header = fh.read(12)
            (length,) = struct.unpack("<Q", header[:8])
            fh.seek(12 + length // 2)
            b0 = fh.read(1)
            fh.seek(12 + length // 2)
            fh.write(bytes([b0[0] ^ 0xFF]))
        ds = _parsed_ds(paths)
        with ReaderPool(ds.reader_work(train=False), procs=3,
                        on_corrupt=ds._count_corrupt) as pool:
            n = sum(1 for _ in pool)
        # every worker reads the same stream; the parent must route the
        # MAX cumulative count (1), not the sum across workers (3)
        assert ds.corrupt_records == 1
        assert n == (2 * 24 - 1) // 8


# ----------------------------------------------------------------------
# DeviceFeed integration: the shutdown-ordering regression
# ----------------------------------------------------------------------

class TestFeedIntegration:
    def test_feed_over_pool_strict_order(self):
        pool = ReaderPool(_ident_chunks(n=40, chunk=4), procs=2)
        with DeviceFeed(pool, put_fn=lambda b: b * 10,
                        prefetch_depth=2) as feed:
            got = [item.payload for item in feed]
        assert len(got) == 10
        for k, g in enumerate(got):
            np.testing.assert_array_equal(
                g, np.asarray(list(range(40))[k * 4:(k + 1) * 4],
                              np.int64) * 10)

    def test_worker_killed_with_queue_full_no_deadlock(self):
        """THE regression this PR fixes in DeviceFeed shutdown ordering:
        reader children SIGKILLed while the bounded queues are full must
        surface the worker's failure at the consumer within a bounded
        time — and feed.close() must reap everything — instead of the
        consumer and the feed join deadlocking against a dead producer."""
        def slow(chunk):
            time.sleep(0.005)
            return np.asarray(chunk)

        pool = ReaderPool(ChunkWork(list(range(4000)), 2, slow), procs=2,
                          window=4)
        feed = DeviceFeed(pool, put_fn=lambda b: b, prefetch_depth=1)
        it = iter(feed)
        next(it)
        time.sleep(0.3)  # queues fill: workers block mid-put
        for p in list(pool._workers.values()):
            p.kill()
        t0 = time.monotonic()
        with pytest.raises(RuntimeError) as ei:
            for _ in range(10_000):
                next(it)
        assert time.monotonic() - t0 < 10.0
        assert isinstance(ei.value.__cause__, ReaderWorkerError)
        t0 = time.monotonic()
        feed.close()
        assert time.monotonic() - t0 < 8.0

    def test_early_break_tears_down_pool_through_feed_close(self):
        pool = ReaderPool(_ident_chunks(n=4000, chunk=2), procs=3)
        feed = DeviceFeed(pool, put_fn=lambda b: b, prefetch_depth=2)
        it = iter(feed)
        for _ in range(3):
            next(it)
        feed.close()  # close-through: no explicit pool.close() needed
        assert pool._closed
        assert all(not p.is_alive() for p in pool._workers.values())

    def test_inline_feed_closes_through(self):
        pool = ReaderPool(_ident_chunks(n=400, chunk=2), procs=2)
        feed = InlineFeed(pool, put_fn=lambda b: b)
        next(iter(feed))
        feed.close()
        assert pool._closed


# ----------------------------------------------------------------------
# autoscaler
# ----------------------------------------------------------------------

class TestAutoscaler:
    def test_grows_under_stall_and_exports_gauge(self):
        from bigdl_tpu import obs as _obs

        def slow(chunk):
            time.sleep(0.002)
            return np.asarray(chunk)

        pool = ReaderPool(ChunkWork(list(range(4000)), 2, slow), procs=1,
                          max_procs=3, autoscale=True, cooldown_s=0.05)
        try:
            it = iter(pool)
            for _ in range(60):
                next(it)
                pool.note_feed(0.05, 1)  # consumer reports 50 ms stalls
                if pool.procs >= 2:
                    break
            assert pool.procs >= 2
            snap = _obs.registry().snapshot()
            assert snap["gauges"]["feed/reader_procs"] == pool.procs
        finally:
            pool.close()

    def test_shrinks_when_idle_with_hysteresis(self):
        pool = ReaderPool(_ident_chunks(n=8000, chunk=2), procs=3,
                          max_procs=3, autoscale=True, cooldown_s=0.02)
        try:
            it = iter(pool)
            for _ in range(80):
                next(it)
                pool.note_feed(0.0, 3)  # queue always ahead: zero stall
                if pool.procs == 1:
                    break
                time.sleep(0.001)
            assert pool.procs < 3
            # hysteresis floor: never below 1
            assert pool.procs >= 1
        finally:
            pool.close()

    def test_off_by_default(self):
        pool = ReaderPool(_ident_chunks(), procs=2, max_procs=4)
        try:
            for _ in range(20):
                pool.note_feed(1.0, 0)
            assert pool.procs == 2
        finally:
            pool.close()


# ----------------------------------------------------------------------
# trainer integration: bitwise parity + chaos kill->resume
# ----------------------------------------------------------------------

class TestTrainerParity:
    def _train(self, procs, tmp_path, tag):
        from bigdl_tpu.utils.summary import TrainSummary

        RandomGenerator.set_seed(7)
        o = optim.LocalOptimizer(_mlp(), _class_ds(), nn.ClassNLLCriterion(),
                                 optim_method=SGD(learning_rate=0.3),
                                 end_trigger=Trigger.max_epoch(2))
        o.set_feed(2, reader_procs=procs)
        o.set_train_summary(TrainSummary(str(tmp_path), tag))
        o.optimize()
        losses = [v for _, v in o.train_summary.read_scalar("Loss")]
        params = [np.asarray(l) for l in jax.tree_util.tree_leaves(o.params)]
        return losses, params

    def test_bitwise_loss_and_param_parity_readers_on_vs_off(self, tmp_path):
        losses_off, params_off = self._train(0, tmp_path, "off")
        losses_on, params_on = self._train(2, tmp_path, "on")
        assert losses_off == losses_on
        for a, b in zip(params_off, params_on):
            np.testing.assert_array_equal(a, b)


@pytest.mark.chaos
class TestReaderChaosParity:
    def _make(self, procs, epochs=3, seed=42):
        RandomGenerator.set_seed(seed)
        o = optim.LocalOptimizer(_mlp(), _class_ds(), nn.ClassNLLCriterion(),
                                 optim_method=SGD(learning_rate=0.3),
                                 end_trigger=Trigger.max_epoch(epochs))
        o.set_feed(2, reader_procs=procs)
        o.set_fault_tolerance(backoff_base_s=0.0)
        return o

    def test_kill_and_resume_losses_bitwise_equal(self, tmp_path):
        """Chaos kill at step 8 (mid-epoch-2 with 6-step epochs), resume
        from the checkpoint in a 'fresh process': per-step losses under
        reader_procs=2 match the uninterrupted reader_procs=2 run — and
        the uninterrupted procs=0 run — bitwise."""
        from bigdl_tpu.resilience import (ChaosStepFault, StepFaultInjector,
                                          committed_steps)
        from bigdl_tpu.utils.summary import TrainSummary

        base = self._make(0)
        base.set_train_summary(TrainSummary(str(tmp_path / "a"), "base"))
        base.optimize()
        base_losses = dict(base.train_summary.read_scalar("Loss"))

        root = str(tmp_path / "ck")
        o = self._make(2)
        o.set_checkpoint(root, Trigger.several_iteration(4))
        o.set_chaos(StepFaultInjector(fail_steps=(8,)))
        o.set_fault_tolerance(max_restarts=0, backoff_base_s=0.0)
        with pytest.raises(ChaosStepFault):
            o.optimize()
        assert committed_steps(root)

        RandomGenerator.set_seed(999)  # ckpt seed must win
        o2 = optim.LocalOptimizer(_mlp(), _class_ds(),
                                  nn.ClassNLLCriterion(),
                                  optim_method=SGD(learning_rate=0.3),
                                  end_trigger=Trigger.max_epoch(3))
        o2.set_feed(2, reader_procs=2)
        o2.set_train_summary(TrainSummary(str(tmp_path / "b"), "res"))
        o2.resume_from(root)
        o2.optimize()
        res_losses = dict(o2.train_summary.read_scalar("Loss"))
        assert res_losses
        for step, loss in res_losses.items():
            assert loss == base_losses[step], (
                f"step {step}: resumed pooled loss {loss!r} != "
                f"uninterrupted {base_losses[step]!r}")

    def test_dead_reader_worker_is_retryable(self, tmp_path, monkeypatch):
        """A reader child dying mid-training is a transient fault: the
        bounded-restart ladder resumes from the checkpoint and finishes
        with the same final params as an undisturbed run.  The kill is
        deterministic: the SECOND epoch's pool (epoch 1 committed a
        checkpoint at step 4) has its workers SIGKILLed at creation."""
        import bigdl_tpu.dataset.readers as readers_mod

        base = self._make(0)
        base.optimize()
        base_leaves = [np.asarray(l)
                       for l in jax.tree_util.tree_leaves(base.params)]

        real = readers_mod.make_reader_source
        made = []

        def sabotaged(dataset, train, **kw):
            pool = real(dataset, train, **kw)
            if pool is not None:
                made.append(pool)
                if len(made) == 2:  # epoch 2's pool: murder its workers
                    for p in list(pool._workers.values()):
                        p.kill()
            return pool

        monkeypatch.setattr(readers_mod, "make_reader_source", sabotaged)
        o = self._make(2)
        o.set_checkpoint(str(tmp_path / "ck"), Trigger.several_iteration(4))
        o.set_fault_tolerance(max_restarts=2, backoff_base_s=0.0)
        o.optimize()
        assert len(made) >= 3  # the sabotaged pool WAS replaced by a restart
        leaves = [np.asarray(l)
                  for l in jax.tree_util.tree_leaves(o.params)]
        for a, b in zip(base_leaves, leaves):
            np.testing.assert_array_equal(a, b)
