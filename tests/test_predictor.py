"""Predictor / Evaluator / PredictionService tests.

Models the reference's Predictor/Evaluator specs (optim/Predictor.scala,
optim/Evaluator.scala) including the ragged-final-batch path and the
mesh-sharded batch path on the 8-virtual-device CPU mesh.
"""

import io
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core.engine import Engine, AXIS_DATA
from bigdl_tpu.optim import (
    Evaluator,
    PredictionService,
    Predictor,
    Top1Accuracy,
    Loss,
)

# heavyweight tier: differential oracles / trainers / registry sweeps;
# the quick tier is 'pytest -m "not slow"' (README Testing)
pytestmark = pytest.mark.slow



@pytest.fixture(scope="module")
def small_model():
    model = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 4),
                          nn.LogSoftMax())
    params, state, _ = model.build(jax.random.PRNGKey(0), (8, 6))
    return model, params, state


def test_predict_matches_direct_forward(small_model):
    model, params, state = small_model
    x = np.random.RandomState(0).randn(20, 6).astype(np.float32)
    pred = Predictor(model, params, state, batch_size=8)
    y = pred.predict(x)
    direct, _ = model.apply(params, state, jnp.asarray(x), training=False)
    np.testing.assert_allclose(y, np.asarray(direct), rtol=1e-5, atol=1e-6)
    assert y.shape == (20, 4)  # 8 + 8 + ragged 4, un-padded on output


def test_predict_class(small_model):
    model, params, state = small_model
    x = np.random.RandomState(1).randn(10, 6).astype(np.float32)
    pred = Predictor(model, params, state, batch_size=4)
    cls = pred.predict_class(x)
    assert cls.shape == (10,)
    assert cls.dtype in (np.int32, np.int64)
    direct, _ = model.apply(params, state, jnp.asarray(x), training=False)
    np.testing.assert_array_equal(cls, np.argmax(np.asarray(direct), axis=-1))


def test_predict_sharded_over_mesh(small_model):
    model, params, state = small_model
    mesh = Engine.build_mesh(**{AXIS_DATA: 8})
    x = np.random.RandomState(2).randn(16, 6).astype(np.float32)
    pred = Predictor(model, params, state, mesh=mesh, batch_size=16)
    y = pred.predict(x)
    direct, _ = model.apply(params, state, jnp.asarray(x), training=False)
    np.testing.assert_allclose(y, np.asarray(direct), rtol=1e-5, atol=1e-6)


def test_evaluator_counts_and_accuracy(small_model):
    model, params, state = small_model
    rs = np.random.RandomState(3)
    x = rs.randn(21, 6).astype(np.float32)  # ragged: 21 = 8+8+5
    out, _ = model.apply(params, state, jnp.asarray(x), training=False)
    y = np.argmax(np.asarray(out), axis=-1).astype(np.int32)

    ev = Evaluator(model)
    results = ev.test(params, state, _zip_dataset(x, y),
                      [Top1Accuracy(), Loss(nn.ClassNLLCriterion())],
                      batch_size=8)
    acc, count = results[0].result()
    assert count == 21  # padded rows must not inflate the count
    assert acc == pytest.approx(1.0)  # labels are the model's own argmax


def _zip_dataset(x, y):
    from bigdl_tpu.dataset.minibatch import MiniBatch
    bs = 8
    return [MiniBatch(x[i:i + bs], y[i:i + bs]) for i in range(0, len(x), bs)]


def test_prediction_service_concurrent(small_model):
    model, params, state = small_model
    svc = PredictionService(model, params, state, concurrency=2, batch_size=4)
    results = {}

    def worker(i):
        x = np.full((4, 6), i, np.float32)
        results[i] = svc.predict(x)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 6
    for i, y in results.items():
        direct, _ = model.apply(params, state,
                                jnp.full((4, 6), i, jnp.float32), training=False)
        np.testing.assert_allclose(y, np.asarray(direct), rtol=1e-5, atol=1e-6)


def test_predict_multi_input_table(small_model):
    from bigdl_tpu.core.table import Table
    model = nn.Sequential(nn.CAddTable(), nn.Linear(3, 2))
    params, state, _ = model.build(jax.random.PRNGKey(0),
                                   Table((4, 3), (4, 3)))
    a = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    b = np.random.RandomState(1).randn(4, 3).astype(np.float32)
    pred = Predictor(model, params, state, batch_size=4)
    y = pred.predict(Table(a, b))
    direct, _ = model.apply(params, state, Table(jnp.asarray(a), jnp.asarray(b)))
    assert y.shape == (4, 2)
    np.testing.assert_allclose(y, np.asarray(direct), rtol=1e-5, atol=1e-6)

    svc = PredictionService(model, params, state, batch_size=4)
    import io as _io
    buf = _io.BytesIO()
    np.savez(buf, a=a, b=b)
    resp = svc.predict_bytes(buf.getvalue())
    with np.load(_io.BytesIO(resp)) as npz:
        np.testing.assert_allclose(npz["output"], np.asarray(direct),
                                   rtol=1e-5, atol=1e-6)


def test_prediction_service_bytes_api(small_model):
    model, params, state = small_model
    svc = PredictionService(model, params, state, batch_size=2)
    x = np.random.RandomState(5).randn(2, 6).astype(np.float32)
    buf = io.BytesIO()
    np.savez(buf, input=x)
    resp = svc.predict_bytes(buf.getvalue())
    with np.load(io.BytesIO(resp)) as npz:
        y = npz["output"]
    direct, _ = model.apply(params, state, jnp.asarray(x), training=False)
    np.testing.assert_allclose(y, np.asarray(direct), rtol=1e-5, atol=1e-6)
