"""Smoke tests for the round-5 convergence entry points: generator ->
real-format files -> production loader -> DistriOptimizer, end to end on
tiny sizes (the full-size runs + metrics live in BENCH_APPENDIX "Real
training runs" / docs/training_runs.md)."""

import json
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow


def test_gen_mnist_and_train(tmp_path):
    import tools.gen_mnist as gen
    import examples.train_mnist as train

    out = str(tmp_path / "mnist")
    gen.main(["--out", out, "--n-train", "512", "--n-test", "128"])
    # real idx format: the production loader parses what was written
    from bigdl_tpu.dataset import load_mnist

    x, y = load_mnist(out, "train")
    assert x.shape == (512, 28, 28, 1) and y.shape == (512,)
    res = train.main(["--data-dir", out, "--epochs", "5", "--batch-size",
                      "64", "--decay-epoch", "0",
                      "--checkpoint", str(tmp_path / "ckpt"),
                      "--summary", str(tmp_path / "tb")])
    assert res["test_acc"] > 0.5  # 40 steps on 512 imgs: well past chance
    assert os.path.isdir(str(tmp_path / "ckpt"))
    assert any("events.out.tfevents" in f
               for _, _, fs in os.walk(str(tmp_path / "tb")) for f in fs)


def test_gen_ptb_and_train(tmp_path):
    import tools.gen_ptb as gen
    import examples.train_ptb as train

    out = str(tmp_path / "ptb")
    gen.main(["--out", out, "--vocab-size", "2000",
              "--max-train-tokens", "30000", "--pkgs", "jax"])
    for split in ("train", "valid", "test"):
        assert os.path.exists(os.path.join(out, f"ptb.{split}.txt"))
    res = train.main(["--data-dir", out, "--vocab-size", "2000",
                      "--embed", "32", "--hidden", "32", "--layers", "1",
                      "--batch-size", "8", "--num-steps", "16",
                      "--epochs", "1", "--keep-prob", "1.0"])
    # one epoch on 30k tokens: ppl must at least beat uniform (=vocab)
    assert res["test_ppl"] < 2000
    assert np.isfinite(res["valid_ppl"])
