"""Health subsystem: divergence watchdog, checkpoint integrity, hang
detection (bigdl_tpu.health).

The acceptance contract: with a NaNInjector firing persistent NaN at step
k, the watchdog's rollback restores the last HEALTHY checkpoint and the
run completes with params BITWISE-equal to a run whose bad steps were
skipped on device and never landed — feed on or off, under strict
transfers.  Plus the integrity half: a bit-flipped committed shard is
detected by its per-leaf CRC32C and the restore fallback chain walks past
it; and the hang half: a wedged feed blows its phase deadline, raises the
retryable StalledStep, and the restart loop recovers the run.
"""

import os
import struct
import time

import numpy as np
import pytest

import jax

import bigdl_tpu.nn as nn
from bigdl_tpu import optim
from bigdl_tpu.core.random import RandomGenerator
from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch
from bigdl_tpu.health import (
    INTEGRITY_COUNTERS,
    CorruptCheckpointError,
    DivergenceAbort,
    DivergenceWatchdog,
    HangWatchdog,
    NumericDivergence,
    StalledStep,
    WatchdogConfig,
    dump_thread_stacks,
    leaf_crc,
    reset_counters,
    tree_crcs,
    verify_enabled,
    verify_flat,
)
from bigdl_tpu.optim import SGD, Trigger
from bigdl_tpu.resilience import (
    AsyncCheckpointer,
    BitFlipCheckpointFault,
    NaNInjector,
)
from bigdl_tpu.utils.checkpoint import (
    checkpoint_health,
    latest_checkpoint,
    load_checkpoint,
    save_checkpoint,
    verify_checkpoint,
)


def make_dataset(n=64, dim=8, batch=8, seed=7):
    rs = np.random.RandomState(seed)
    samples = [Sample.from_ndarray(rs.randn(dim).astype(np.float32),
                                   rs.randn(4).astype(np.float32))
               for _ in range(n)]
    return ArrayDataSet(samples).transform(SampleToMiniBatch(batch))


def param_leaves(o):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(o.params)]


def assert_bitwise_equal(a_leaves, b_leaves):
    assert len(a_leaves) == len(b_leaves)
    for a, b in zip(a_leaves, b_leaves):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)


def _xor_bytes(path, offsets, mask=0x80):
    with open(path, "r+b") as fh:
        for off in offsets:
            fh.seek(off)
            b = fh.read(1)
            fh.seek(off)
            fh.write(bytes([b[0] ^ mask]))


def _corrupt_npz(ckpt_dir, name="params.npz"):
    """Flip bytes spread through the file: at least one lands in a zip
    member's data or structure, so np.load or the leaf CRC must object."""
    p = os.path.join(ckpt_dir, name)
    size = os.path.getsize(p)
    _xor_bytes(p, [size // 3, size // 2, 2 * size // 3])


def _record_offsets(path):
    """(frame_offset, data_length) per record of a TFRecord file."""
    offs = []
    with open(path, "rb") as fh:
        off = 0
        while True:
            fh.seek(off)
            header = fh.read(12)
            if not header:
                return offs
            (length,) = struct.unpack("<Q", header[:8])
            offs.append((off, length))
            off += 12 + length + 4


# ----------------------------------------------------------------------
# Integrity primitives
# ----------------------------------------------------------------------

class TestIntegrityPrimitives:
    def test_leaf_crc_deterministic_and_byte_sensitive(self):
        a = np.arange(32, dtype=np.float32).reshape(4, 8)
        assert leaf_crc(a) == leaf_crc(a.copy())
        b = a.copy()
        b.view(np.uint8).reshape(-1)[5] ^= 0x01
        assert leaf_crc(b) != leaf_crc(a)

    def test_leaf_crc_folds_dtype_and_shape(self):
        a = np.arange(8, dtype=np.float32)
        assert leaf_crc(a) != leaf_crc(a.view(np.int32))
        assert leaf_crc(a) != leaf_crc(a.reshape(2, 4))

    def test_verify_flat_names_the_offending_leaf(self):
        flat = {"w": np.ones(4, np.float32), "b": np.zeros(2, np.float32)}
        crcs = tree_crcs(flat)
        verify_flat(flat, crcs, "ok")  # clean pass

        bad = dict(flat, w=np.full(4, 7.0, np.float32))
        with pytest.raises(CorruptCheckpointError, match="w"):
            verify_flat(bad, crcs, "here")
        with pytest.raises(CorruptCheckpointError, match="missing from file"):
            verify_flat({"w": flat["w"]}, crcs, "here")
        with pytest.raises(CorruptCheckpointError, match="not in stored"):
            verify_flat(dict(flat, extra=np.ones(1, np.float32)), crcs, "here")

    def test_verify_enabled_env(self, monkeypatch):
        monkeypatch.delenv("BIGDL_TPU_CKPT_VERIFY", raising=False)
        assert verify_enabled(None) is True  # integrity is opt-out
        monkeypatch.setenv("BIGDL_TPU_CKPT_VERIFY", "0")
        assert verify_enabled(None) is False
        assert verify_enabled(True) is True  # explicit override wins
        monkeypatch.setenv("BIGDL_TPU_CKPT_VERIFY", "on")
        assert verify_enabled(None) is True


# ----------------------------------------------------------------------
# Divergence policy ladder (host-side, no device)
# ----------------------------------------------------------------------

class TestDivergenceLadder:
    def test_skip_backoff_rollback_abort_progression(self):
        wd = DivergenceWatchdog(WatchdogConfig(
            skip_limit=1, backoff_factor=0.5, max_backoffs=1,
            max_rollbacks=1, hang_deadlines=None))
        assert wd.observe(0, True) == "ok"
        assert wd.observe(1, False) == "skip"
        assert wd.observe(2, False) == "lr_backoff"
        assert wd.lr_scale == 0.5 and wd.backoffs == 1
        assert wd.observe(3, False) == "skip"  # backoff reset the streak
        with pytest.raises(NumericDivergence) as ei:
            wd.observe(4, False)
        assert ei.value.bad_steps == (1, 2, 3, 4)
        assert wd.marked == {1, 2, 3, 4}
        wd.note_rollback()
        assert wd.rollbacks == 1
        # replaying a marked step skips without re-escalating
        assert wd.observe(3, False) == "skip"
        assert wd.observe(5, True) == "ok"
        # rollback budget spent: the next escalation aborts
        assert wd.observe(6, False) == "skip"
        with pytest.raises(DivergenceAbort):
            wd.observe(7, False)

    def test_adopt_marked_from_checkpoint_stamp(self):
        wd = DivergenceWatchdog(WatchdogConfig(skip_limit=0,
                                               hang_deadlines=None))
        wd.adopt_marked([7, 8])
        assert wd.observe(7, False) == "skip"  # no escalation on marked

    def test_verdict_lag_window(self):
        wd = DivergenceWatchdog(WatchdogConfig(skip_limit=5, max_lag=4,
                                               hang_deadlines=None))
        wd.observe(2, False)
        # unresolved bad run: any snapshot now is suspect
        assert wd.verdict(10)["verdict"] == "diverged"
        wd.observe(3, True)
        # resolved, and step 2 is outside the lag window of step 10
        assert wd.verdict(10)["verdict"] == "healthy"
        v = wd.verdict(4)  # ...but inside the window of step 4
        assert v["verdict"] == "diverged" and v["bad_steps"] == [2]


# ----------------------------------------------------------------------
# Hang watchdog
# ----------------------------------------------------------------------

class TestHangWatchdog:
    def test_deadline_breach_raises_once_then_clears(self):
        hw = HangWatchdog({"feed_next": 0.1}, poll_s=0.02)
        with hw:
            with hw.phase("feed_next"):
                time.sleep(0.4)
            with pytest.raises(StalledStep) as ei:
                hw.check()
            assert ei.value.phase == "feed_next"
            assert ei.value.elapsed_s > ei.value.deadline_s
            hw.check()  # the pending stall is consumed: no double kill
            assert hw.stalls and hw.stalls[0][0] == "feed_next"
            # a phase with no configured deadline never stalls
            with hw.phase("step_dispatch"):
                time.sleep(0.3)
            hw.check()
            # clear() drops a pending stall (restart-resume path)
            with hw.phase("feed_next"):
                time.sleep(0.4)
            hw.clear()
            hw.check()

    def test_dump_thread_stacks_lists_main(self):
        assert "MainThread" in dump_thread_stacks()


# ----------------------------------------------------------------------
# Checkpoint integrity: CRC verify + restore fallback chain
# ----------------------------------------------------------------------

class TestCheckpointIntegrity:
    def test_roundtrip_verifies_and_counts(self, tmp_path):
        reset_counters()
        root = str(tmp_path)
        params = {"w": np.arange(24, dtype=np.float32).reshape(4, 6)}
        d = save_checkpoint(root, 3, params)
        meta = verify_checkpoint(d)
        assert "params.npz" in meta["integrity"]
        loaded, _, _, _ = load_checkpoint(
            d, {"w": np.zeros((4, 6), np.float32)}, verify=True)
        np.testing.assert_array_equal(loaded["w"], params["w"])
        assert INTEGRITY_COUNTERS["verified"] >= 1

    def test_corrupt_shard_detected_and_fallback(self, tmp_path):
        reset_counters()
        root = str(tmp_path)
        params = {"w": np.arange(64, dtype=np.float32)}
        save_checkpoint(root, 1, params)
        d2 = save_checkpoint(root, 2, params)
        _corrupt_npz(d2)
        with pytest.raises(CorruptCheckpointError):
            verify_checkpoint(d2)
        with pytest.raises(CorruptCheckpointError):
            load_checkpoint(d2, {"w": np.zeros(64, np.float32)}, verify=True)
        # fast path (no verify) still answers newest-committed ...
        assert latest_checkpoint(root).endswith("ckpt_2")
        # ... the verified chain walks past the rotten one
        assert latest_checkpoint(root, verify=True).endswith("ckpt_1")
        assert INTEGRITY_COUNTERS["corrupt_skipped"] >= 1

    def test_require_healthy_skips_diverged_stamp(self, tmp_path):
        reset_counters()
        root = str(tmp_path)
        params = {"w": np.ones(8, np.float32)}
        save_checkpoint(root, 1, params, driver_state={
            "health": {"verdict": "healthy", "bad_steps": []}})
        d2 = save_checkpoint(root, 2, params, driver_state={
            "health": {"verdict": "diverged", "bad_steps": [9]}})
        assert checkpoint_health(d2)["verdict"] == "diverged"
        assert latest_checkpoint(root).endswith("ckpt_2")
        assert latest_checkpoint(
            root, require_healthy=True).endswith("ckpt_1")
        assert INTEGRITY_COUNTERS["unhealthy_skipped"] >= 1

    @pytest.mark.chaos
    def test_bitflip_after_commit_skipped_on_restore(self, tmp_path):
        """BitFlipCheckpointFault rots a COMMITTED shard behind the
        writer's back; the CRC32C chain must catch it on restore."""
        reset_counters()
        root = str(tmp_path)
        fault = BitFlipCheckpointFault(fail_on_save=3, file="params.npz",
                                       n_bytes=8)
        params = {"w": np.arange(64, dtype=np.float32)}
        with AsyncCheckpointer(root, post_commit=fault) as w:
            for step in (1, 2, 3):
                w.save_async(step, params)
            w.wait()
            assert not w.failed  # the write itself succeeded; rot came later
        assert fault.fired and fault.fired[0].endswith("ckpt_3")
        assert latest_checkpoint(root).endswith("ckpt_3")
        assert latest_checkpoint(root, verify=True).endswith("ckpt_2")
        assert INTEGRITY_COUNTERS["corrupt_skipped"] >= 1
        with pytest.raises(CorruptCheckpointError):
            verify_checkpoint(os.path.join(root, "ckpt_3"))


# ----------------------------------------------------------------------
# Trainer integration: the policy ladder end to end
# ----------------------------------------------------------------------

def run_training(feed, strict, injector, cfg, root=None, max_restarts=0,
                 seed=42, epochs=2):
    RandomGenerator.set_seed(seed)
    model = nn.Sequential(nn.Linear(8, 4))
    o = optim.LocalOptimizer(model, make_dataset(), nn.MSECriterion(),
                             optim_method=SGD(learning_rate=0.05),
                             end_trigger=Trigger.max_epoch(epochs))
    o.set_fault_tolerance(max_restarts=max_restarts, backoff_base_s=0.0)
    o.set_feed(feed)
    if strict:
        o.set_strict_transfers(True)
    o.set_watchdog(cfg)
    if injector is not None:
        o.set_chaos(injector)
    if root is not None:
        o.set_checkpoint(root, Trigger.several_iteration(2))
    o.optimize()
    return o


class TestTrainerWatchdog:
    @pytest.mark.chaos
    def test_transient_nan_absorbed_by_skip_rung(self):
        o = run_training(
            0, False, NaNInjector(fail_steps=(3,), persistent=False),
            WatchdogConfig(skip_limit=3, max_backoffs=0, max_rollbacks=0,
                           hang_deadlines=None))
        wd = o._watchdog
        assert wd.skipped == 1 and wd.bad_steps == {3}
        assert wd.backoffs == 0 and wd.rollbacks == 0 and wd.lr_scale == 1.0
        assert o._driver_state["neval"] == 16
        for leaf in param_leaves(o):
            assert np.isfinite(leaf).all()

    @pytest.mark.chaos
    def test_lr_backoff_rung(self):
        o = run_training(
            0, False, NaNInjector(fail_steps=(4, 5), persistent=False),
            WatchdogConfig(skip_limit=1, backoff_factor=0.5, max_backoffs=1,
                           max_rollbacks=0, hang_deadlines=None))
        wd = o._watchdog
        assert wd.backoffs == 1 and wd.lr_scale == 0.5
        assert o._driver_state["neval"] == 16
        for leaf in param_leaves(o):
            assert np.isfinite(leaf).all()

    @pytest.mark.chaos
    @pytest.mark.parametrize("feed", [0, 2])
    def test_rollback_bitwise_parity(self, tmp_path, feed):
        """The acceptance demo: persistent NaN at steps 5-7 escalates to a
        rollback; the rolled-back run must finish BITWISE-equal to a run
        that only ever skipped those steps on device (the bad updates
        never landed either way) — feed on and off, strict transfers."""
        ref = run_training(
            feed, True, NaNInjector(fail_steps=(5, 6, 7), persistent=True),
            WatchdogConfig(skip_limit=100, max_backoffs=0, max_rollbacks=0,
                           hang_deadlines=None))
        roll = run_training(
            feed, True, NaNInjector(fail_steps=(5, 6, 7), persistent=True),
            WatchdogConfig(skip_limit=2, max_backoffs=0, max_rollbacks=1,
                           hang_deadlines=None),
            root=str(tmp_path / f"ck{feed}"))
        wd = roll._watchdog
        assert wd.rollbacks == 1
        assert wd.marked == {5, 6, 7}
        assert roll._driver_state["neval"] == ref._driver_state["neval"] == 16
        assert roll._driver_state["loss"] == ref._driver_state["loss"]
        assert_bitwise_equal(param_leaves(ref), param_leaves(roll))

    @pytest.mark.chaos
    def test_rollback_without_checkpoint_raises(self):
        with pytest.raises(NumericDivergence):
            run_training(
                0, False, NaNInjector(fail_steps=(3,), persistent=True),
                WatchdogConfig(skip_limit=0, max_backoffs=0, max_rollbacks=1,
                               hang_deadlines=None))

    @pytest.mark.chaos
    def test_abort_when_ladder_exhausted(self):
        with pytest.raises(DivergenceAbort):
            run_training(
                0, False, NaNInjector(fail_steps=(3,), persistent=True),
                WatchdogConfig(skip_limit=0, max_backoffs=0, max_rollbacks=0,
                               hang_deadlines=None))


# ----------------------------------------------------------------------
# Hang watchdog end to end: a wedged feed recovered by restart
# ----------------------------------------------------------------------

class _StallOnce:
    """Dataset proxy whose FIRST train pass sleeps mid-epoch — a wedged
    feed the hang watchdog must flag; replays stream normally."""

    def __init__(self, inner, after=3, stall_s=1.2):
        self._inner = inner
        self._after = after
        self._stall_s = stall_s
        self.train_calls = 0
        self.stalled = 0

    def data(self, train):
        src = self._inner.data(train=train)
        if not train:
            return src
        self.train_calls += 1
        return src if self.train_calls > 1 else self._stalling(src)

    def _stalling(self, src):
        for i, item in enumerate(src):
            if i == self._after:
                self.stalled += 1
                time.sleep(self._stall_s)
            yield item

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_inner"), name)


class TestHangRecovery:
    @pytest.mark.chaos
    def test_stalled_feed_recovered_by_restart(self, tmp_path):
        ref = run_training(0, False, None,
                           WatchdogConfig(hang_deadlines=None))
        RandomGenerator.set_seed(42)
        model = nn.Sequential(nn.Linear(8, 4))
        ds = _StallOnce(make_dataset())
        o = optim.LocalOptimizer(model, ds, nn.MSECriterion(),
                                 optim_method=SGD(learning_rate=0.05),
                                 end_trigger=Trigger.max_epoch(2))
        o.set_fault_tolerance(max_restarts=2, backoff_base_s=0.0)
        o.set_feed(0)
        o.set_watchdog(WatchdogConfig(hang_deadlines={"feed_next": 0.3},
                                      hang_poll_s=0.05))
        o.set_checkpoint(str(tmp_path / "ck"), Trigger.several_iteration(2))
        o.optimize()
        assert ds.stalled == 1
        assert ds.train_calls >= 3  # the stalled epoch was re-entered
        assert o._hang is None  # monitor thread stopped on exit
        assert o._driver_state["neval"] == 16
        assert_bitwise_equal(param_leaves(ref), param_leaves(o))


# ----------------------------------------------------------------------
# Serving: per-request non-finite output guard + registry CRC verify
# ----------------------------------------------------------------------

class TestServingHealth:
    def _model(self):
        model = nn.Sequential(nn.Linear(6, 4))
        params, state, _ = model.build(jax.random.PRNGKey(0), (8, 6))
        return model, params, state

    def test_reject_nonfinite_guard(self):
        from bigdl_tpu.serving import NonFiniteOutput, ServingRuntime

        model, params, state = self._model()
        bad = jax.tree_util.tree_map(
            lambda a: np.full(np.shape(a), np.nan,
                              np.asarray(a).dtype), params)
        x = np.zeros((2, 6), np.float32)
        example = np.zeros((1, 6), np.float32)
        with ServingRuntime(model, bad, state, buckets=(1, 8),
                            example_input=example,
                            reject_nonfinite=True) as rt:
            with pytest.raises(NonFiniteOutput):
                rt.predict(x, timeout=30.0)
            assert rt.metrics.snapshot()["rejected_nonfinite"] == 1
            # swapping in a finite version heals the endpoint
            rt.swap("v1", params, state)
            out = rt.predict(x, timeout=30.0)
            assert np.isfinite(np.asarray(out)).all()

    def test_guard_off_passes_nan_through(self):
        from bigdl_tpu.serving import ServingRuntime

        model, params, state = self._model()
        bad = jax.tree_util.tree_map(
            lambda a: np.full(np.shape(a), np.nan,
                              np.asarray(a).dtype), params)
        with ServingRuntime(model, bad, state, buckets=(1, 8),
                            example_input=np.zeros((1, 6),
                                                   np.float32)) as rt:
            out = rt.predict(np.zeros((2, 6), np.float32), timeout=30.0)
            assert not np.isfinite(np.asarray(out)).any()
            assert rt.metrics.snapshot()["rejected_nonfinite"] == 0

    def test_registry_register_from_checkpoint_verifies(self, tmp_path):
        from bigdl_tpu.serving import ModelRegistry

        reset_counters()
        root = str(tmp_path)
        save_checkpoint(root, 1, {"w": np.ones((2, 3), np.float32)})
        d2 = save_checkpoint(root, 2, {"w": np.full((2, 3), 2.0,
                                                    np.float32)})
        _corrupt_npz(d2)
        r = ModelRegistry()
        r.register("v0", {"w": np.zeros((2, 3), np.float32)})
        mv = r.register_from_checkpoint(root)
        assert mv.source.endswith("ckpt_1")  # walked past the rotten one
        assert INTEGRITY_COUNTERS["corrupt_skipped"] >= 1
        with pytest.raises(CorruptCheckpointError):
            r.register_from_checkpoint(d2)  # directly named: loud failure


# ----------------------------------------------------------------------
# TFRecord skip_corrupt policy
# ----------------------------------------------------------------------

class TestTFRecordSkipCorrupt:
    def _shard(self, tmp_path, n=6):
        from bigdl_tpu.dataset.tfrecord import write_sample_shards

        rs = np.random.RandomState(0)
        samples = [Sample.from_ndarray(rs.randn(8).astype(np.float32),
                                       rs.randn(4).astype(np.float32))
                   for _ in range(n)]
        return write_sample_shards(samples, str(tmp_path), n_shards=1)[0]

    def test_data_crc_skipped_and_counted(self, tmp_path):
        from bigdl_tpu.dataset.tfrecord import (PrefetchRecordReader,
                                                read_tfrecords)

        path = self._shard(tmp_path)
        offs = _record_offsets(path)
        assert len(offs) == 6
        _xor_bytes(path, [offs[2][0] + 12 + 5])  # record 2's data region
        with pytest.raises(IOError):
            list(read_tfrecords(path))  # strict default: the run dies
        dropped = [0]
        recs = list(read_tfrecords(path, skip_corrupt=True,
                                   on_corrupt=lambda n: dropped.__setitem__(
                                       0, dropped[0] + n)))
        assert len(recs) == 5 and dropped[0] == 1  # resynced past the rot
        assert len(list(PrefetchRecordReader([path],
                                             skip_corrupt=True))) == 5

    def test_length_crc_still_raises(self, tmp_path):
        """Without a trusted length there is no next frame to resync to:
        skip_corrupt only forgives DATA rot, not framing rot."""
        from bigdl_tpu.dataset.tfrecord import read_tfrecords

        path = self._shard(tmp_path)
        offs = _record_offsets(path)
        _xor_bytes(path, [offs[2][0] + 2])  # inside the length header
        with pytest.raises(IOError):
            list(read_tfrecords(path, skip_corrupt=True))

    def test_parsed_example_dataset_counts_corrupt(self, tmp_path):
        from bigdl_tpu.dataset.tfrecord import (ParsedExampleDataSet,
                                                TFRecordWriter)
        from bigdl_tpu.nn.tf_ops import build_example_proto

        path = str(tmp_path / "ex.tfrecord")
        rs = np.random.RandomState(0)
        with TFRecordWriter(path) as w:
            for i in range(24):
                w.write(build_example_proto(
                    {"x": rs.randn(4).astype(np.float32),
                     "y": np.asarray([i % 3], np.int64)}))
        offs = _record_offsets(path)
        _xor_bytes(path, [offs[1][0] + 12 + 3])

        strict = ParsedExampleDataSet(
            [path], batch_size=4, dense_keys=["x", "y"],
            dense_shapes=[(4,), ()], label_key="y")
        with pytest.raises(IOError):
            list(strict.data(train=False))

        lenient = ParsedExampleDataSet(
            [path], batch_size=4, dense_keys=["x", "y"],
            dense_shapes=[(4,), ()], label_key="y", skip_corrupt=True)
        batches = list(lenient.data(train=False))
        assert len(batches) == 5  # 23 intact records -> 5 full batches
        assert lenient.corrupt_records == 1
