"""Keras-1 weight import: every reference WeightsConverter family
(pyspark/bigdl/keras/converter.py:110-281).

Oracles: tf ops / tf.keras layers where the math survives into TF2
(separable/atrous convs, Bidirectional LSTM, ConvLSTM2D), independent
numpy implementations of the keras-1 layer math elsewhere (Highway,
MaxoutDense, SReLU, LocallyConnected1/2D — gone from TF2).  Weight lists
are constructed in the keras-1 trainable_weights order each converter
documents.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.keras import layers as keras
from bigdl_tpu.keras.converter import model_from_json_config
from bigdl_tpu.keras.topology import Sequential as KSequential
from bigdl_tpu.utils import interop


# heavyweight tier: differential oracles / trainers / registry sweeps;
# the quick tier is 'pytest -m "not slow"' (README Testing)
pytestmark = pytest.mark.slow

RS = np.random.RandomState


def _build_and_import(model, x_shape, layer_weights, seed=0):
    params, state, _ = model.build(jax.random.PRNGKey(seed), x_shape)
    params, state = interop.import_keras_weights(model, params, state,
                                                 layer_weights)
    return params, state


def _run(model, params, state, x):
    y, _ = model.apply(params, state, jnp.asarray(x), training=False)
    return np.asarray(y)


class TestHighway:
    def test_highway_matches_keras1_math(self):
        # keras-1 core.py Highway: t = sigmoid(x W_carry + b_carry);
        # y = act(x W + b) * t + (1 - t) * x;
        # trainable_weights = [W, W_carry, b, b_carry]
        d, b = 5, 3
        rs = RS(0)
        W = rs.randn(d, d).astype(np.float32)
        Wc = rs.randn(d, d).astype(np.float32)
        bb = rs.randn(d).astype(np.float32)
        bc = rs.randn(d).astype(np.float32)
        x = rs.randn(b, d).astype(np.float32)

        t = 1.0 / (1.0 + np.exp(-(x @ Wc + bc)))
        want = np.tanh(x @ W + bb) * t + (1.0 - t) * x

        model = KSequential()
        model.add(keras.Highway(activation="tanh", input_shape=(d,)))
        params, state = _build_and_import(model, (b, d), [[W, Wc, bb, bc]])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_highway_no_bias(self):
        d, b = 4, 2
        rs = RS(1)
        W = rs.randn(d, d).astype(np.float32)
        Wc = rs.randn(d, d).astype(np.float32)
        x = rs.randn(b, d).astype(np.float32)
        t = 1.0 / (1.0 + np.exp(-(x @ Wc)))
        want = np.tanh(x @ W) * t + (1.0 - t) * x

        # bare nn.Highway(with_bias=False) — the composite importer
        # anchors on the nn module, with or without the keras wrapper
        model = nn.Sequential(nn.Highway(d, with_bias=False,
                                         activation=nn.Tanh()))
        params, state = _build_and_import(model, (b, d), [[W, Wc]])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestMaxoutDense:
    def test_maxout_matches_keras1_math(self):
        # keras-1 MaxoutDense: out = max_k (x W[k] + b[k]);
        # W (nb_feature, in, out), b (nb_feature, out)
        din, dout, k, b = 6, 3, 4, 5
        rs = RS(2)
        W = rs.randn(k, din, dout).astype(np.float32)
        bb = rs.randn(k, dout).astype(np.float32)
        x = rs.randn(b, din).astype(np.float32)
        want = np.max(np.einsum("bi,kio->bko", x, W) + bb, axis=1)

        model = KSequential()
        model.add(keras.MaxoutDense(dout, nb_feature=k, input_shape=(din,)))
        params, state = _build_and_import(model, (b, din), [[W, bb]])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestSReLU:
    def test_srelu_matches_keras1_math(self):
        # keras-1 SReLU piecewise, per-element params over the feature
        # shape; trainable_weights = [t_left, a_left, t_right, a_right]
        shape, b = (4, 3), 2
        rs = RS(3)
        tl = rs.randn(*shape).astype(np.float32) - 1.0
        al = rs.rand(*shape).astype(np.float32)
        tr = rs.randn(*shape).astype(np.float32) + 1.0
        ar = rs.rand(*shape).astype(np.float32)
        x = (3.0 * rs.randn(b, *shape)).astype(np.float32)

        want = np.where(x >= tr, tr + ar * (x - tr),
                        np.where(x <= tl, tl + al * (x - tl), x))

        model = KSequential()
        model.add(keras.SReLU(input_shape=shape))
        params, state = _build_and_import(model, (b,) + shape,
                                          [[tl, al, tr, ar]])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_srelu_shared_axes(self):
        shape = (4, 3)
        rs = RS(4)
        pshape = (1, 3)  # shared over axis 1 (H)
        ws = [rs.randn(*pshape).astype(np.float32) for _ in range(4)]
        tl, al, tr, ar = ws
        x = (3.0 * rs.randn(2, *shape)).astype(np.float32)
        want = np.where(x >= tr, tr + ar * (x - tr),
                        np.where(x <= tl, tl + al * (x - tl), x))

        model = KSequential()
        model.add(keras.SReLU(shared_axes=[1], input_shape=shape))
        params, state = _build_and_import(model, (2,) + shape, [ws])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestSeparableConv2D:
    def test_separable_conv_matches_tf(self):
        tf = pytest.importorskip("tensorflow")
        cin, mult, cout, kh, kw = 3, 2, 5, 3, 3
        rs = RS(5)
        dw = rs.randn(kh, kw, cin, mult).astype(np.float32) * 0.3
        pw = rs.randn(1, 1, cin * mult, cout).astype(np.float32) * 0.3
        bias = rs.randn(cout).astype(np.float32)
        x = rs.randn(2, 8, 8, cin).astype(np.float32)

        want = tf.nn.separable_conv2d(x, dw, pw, strides=[1, 1, 1, 1],
                                      padding="VALID").numpy() + bias

        model = KSequential()
        model.add(keras.SeparableConvolution2D(cout, kh, kw,
                                               depth_multiplier=mult,
                                               input_shape=(8, 8, cin)))
        params, state = _build_and_import(model, (2, 8, 8, cin),
                                          [[dw, pw, bias]])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


class TestAtrousConv:
    def test_atrous_conv2d_matches_tf(self):
        tf = pytest.importorskip("tensorflow")
        cin, cout, k, rate = 2, 4, 3, 2
        rs = RS(6)
        W = rs.randn(k, k, cin, cout).astype(np.float32) * 0.3
        bias = rs.randn(cout).astype(np.float32)
        x = rs.randn(2, 9, 9, cin).astype(np.float32)
        want = tf.nn.atrous_conv2d(x, W, rate=rate,
                                   padding="VALID").numpy() + bias

        model = KSequential()
        model.add(keras.AtrousConvolution2D(cout, k, k, atrous_rate=(rate,
                                                                     rate),
                                            input_shape=(9, 9, cin)))
        params, state = _build_and_import(model, (2, 9, 9, cin), [[W, bias]])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_atrous_conv1d_keras1_4d_kernel(self):
        # real keras-1 Convolution1D/AtrousConvolution1D kernels are
        # (filter_length, 1, in, out); the importer must accept that
        tf = pytest.importorskip("tensorflow")
        cin, cout, k, rate, t = 2, 3, 3, 2, 10
        rs = RS(7)
        W4 = rs.randn(k, 1, cin, cout).astype(np.float32) * 0.4
        bias = rs.randn(cout).astype(np.float32)
        x = rs.randn(2, t, cin).astype(np.float32)
        want = tf.nn.convolution(x, W4[:, 0], padding="VALID",
                                 dilations=[rate]).numpy() + bias

        model = KSequential()
        model.add(keras.AtrousConvolution1D(cout, k, atrous_rate=rate,
                                            input_shape=(t, cin)))
        params, state = _build_and_import(model, (2, t, cin), [[W4, bias]])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_conv1d_accepts_4d_kernel(self):
        tf = pytest.importorskip("tensorflow")
        cin, cout, k, t = 3, 4, 3, 8
        rs = RS(8)
        W4 = rs.randn(k, 1, cin, cout).astype(np.float32) * 0.4
        bias = rs.randn(cout).astype(np.float32)
        x = rs.randn(2, t, cin).astype(np.float32)
        want = tf.nn.convolution(x, W4[:, 0], padding="VALID").numpy() + bias

        model = KSequential()
        model.add(keras.Convolution1D(cout, k, input_shape=(t, cin)))
        params, state = _build_and_import(model, (2, t, cin), [[W4, bias]])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


class TestLocallyConnected:
    def _lc1d_oracle(self, x, W, b, k, stride):
        # keras-1 LocallyConnected1D: per-output-frame dense over the
        # flattened (k, C) patch, C fastest
        n, t, c = x.shape
        ot = W.shape[0]
        out = np.zeros((n, ot, W.shape[2]), np.float32)
        for i in range(ot):
            patch = x[:, i * stride:i * stride + k, :].reshape(n, -1)
            out[:, i] = patch @ W[i]
        return out + b

    def test_lc1d_matches_keras1_math(self):
        cin, cout, k, t = 3, 4, 3, 9
        rs = RS(9)
        ot = t - k + 1
        W = rs.randn(ot, k * cin, cout).astype(np.float32) * 0.4
        b = rs.randn(ot, cout).astype(np.float32)
        x = rs.randn(2, t, cin).astype(np.float32)
        want = self._lc1d_oracle(x, W, b, k, 1)

        model = KSequential()
        model.add(keras.LocallyConnected1D(cout, k, input_shape=(t, cin)))
        params, state = _build_and_import(model, (2, t, cin), [[W, b]])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_lc2d_matches_keras1_math(self):
        cin, cout, kh, kw, h, w = 2, 3, 3, 3, 6, 5
        rs = RS(10)
        oh, ow = h - kh + 1, w - kw + 1
        W = rs.randn(oh * ow, kh * kw * cin, cout).astype(np.float32) * 0.4
        b = rs.randn(oh, ow, cout).astype(np.float32)
        x = rs.randn(2, h, w, cin).astype(np.float32)

        # keras-1 LocallyConnected2D: row-major output positions, patch
        # flattened (kh, kw, C) with C fastest
        want = np.zeros((2, oh, ow, cout), np.float32)
        for i in range(oh):
            for j in range(ow):
                patch = x[:, i:i + kh, j:j + kw, :].reshape(2, -1)
                want[:, i, j] = patch @ W[i * ow + j]
        want = want + b

        model = KSequential()
        model.add(keras.LocallyConnected2D(cout, kh, kw,
                                           input_shape=(h, w, cin)))
        params, state = _build_and_import(model, (2, h, w, cin), [[W, b]])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def _keras1_lstm_list(tf_lstm, h):
    """tf.keras fused LSTM kernels (gate order i,f,c,o) -> keras-1
    trainable_weights list [(W,U,b) x gates i,c,f,o]."""
    kernel, rec, bias = [np.asarray(w) for w in tf_lstm.get_weights()]
    sl = {g: slice(i * h, (i + 1) * h)
          for i, g in enumerate(["i", "f", "c", "o"])}
    ws = []
    for g in ["i", "c", "f", "o"]:  # keras-1 build/listing order
        ws += [kernel[:, sl[g]], rec[:, sl[g]], bias[sl[g]]]
    return ws


class TestBidirectional:
    @pytest.mark.parametrize("merge_mode", ["concat", "sum"])
    def test_bidirectional_lstm_matches_tf(self, merge_mode):
        tf = pytest.importorskip("tensorflow")
        f, h, b, t = 3, 4, 2, 5
        layer = tf.keras.layers.Bidirectional(
            tf.keras.layers.LSTM(h, return_sequences=True,
                                 activation="tanh",
                                 recurrent_activation="sigmoid"),
            merge_mode=merge_mode)
        x = RS(11).randn(b, t, f).astype(np.float32)
        want = layer(x).numpy()

        ws = (_keras1_lstm_list(layer.forward_layer, h)
              + _keras1_lstm_list(layer.backward_layer, h))

        model = KSequential()
        model.add(keras.Bidirectional(
            keras.LSTM(h, return_sequences=True, activation="tanh",
                       inner_activation="sigmoid"),
            merge_mode=merge_mode, input_shape=(t, f)))
        params, state = _build_and_import(model, (b, t, f), [ws])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestConvLSTM2D:
    def test_convlstm2d_matches_tf(self):
        tf = pytest.importorskip("tensorflow")
        cin, cout, k, t, hw = 2, 3, 3, 4, 6
        # recurrent_activation='sigmoid' (identical in keras-1 and the TF
        # oracle) isolates the layout/gate-order conversion under test;
        # 'hard_sigmoid' itself changed definition in Keras 3 (x/6+0.5)
        # vs keras-1 (0.2x+0.5), and our cell implements the keras-1 one
        layer = tf.keras.layers.ConvLSTM2D(
            cout, (k, k), padding="same", return_sequences=True,
            activation="tanh", recurrent_activation="sigmoid")
        x = RS(12).randn(2, t, hw, hw, cin).astype(np.float32) * 0.5
        want = layer(x).numpy()

        # tf.keras fused kernels (kh,kw,in,4h) gate order i,f,c,o ->
        # keras-1 12-weight list in i,c,f,o listing order
        kernel, rec, bias = [np.asarray(w) for w in layer.get_weights()]
        sl = {g: slice(i * cout, (i + 1) * cout)
              for i, g in enumerate(["i", "f", "c", "o"])}
        ws = []
        for g in ["i", "c", "f", "o"]:
            ws += [kernel[..., sl[g]], rec[..., sl[g]], bias[sl[g]]]

        model = KSequential()
        model.add(keras.ConvLSTM2D(cout, k, return_sequences=True,
                                   inner_activation="sigmoid",
                                   input_shape=(t, hw, hw, cin)))
        params, state = _build_and_import(model, (2, t, hw, hw, cin), [ws])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


class TestTimeDistributedDense:
    def test_timedistributeddense_json_flow(self):
        b, t, f, o = 2, 4, 3, 5
        rs = RS(13)
        W = rs.randn(f, o).astype(np.float32)
        bias = rs.randn(o).astype(np.float32)
        x = rs.randn(b, t, f).astype(np.float32)
        want = x @ W + bias

        cfg = {"class_name": "Sequential", "config": [
            {"class_name": "TimeDistributedDense",
             "config": {"output_dim": o, "activation": "linear",
                        "batch_input_shape": [None, t, f],
                        "name": "tdd_1"}}]}
        model = model_from_json_config(json.dumps(cfg))
        params, state = _build_and_import(model, (b, t, f), [[W, bias]])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestPReLU:
    def test_prelu_full_shape_import(self):
        # keras-1 PReLU: one learned slope per element over input_shape[1:]
        shape = (4, 3)
        rs = RS(16)
        alphas = rs.rand(*shape).astype(np.float32)
        x = (2.0 * rs.randn(2, *shape)).astype(np.float32)
        want = np.where(x >= 0, x, x * alphas)

        model = KSequential()
        model.add(keras.PReLU(input_shape=shape))
        params, state = _build_and_import(model, (2,) + shape, [[alphas]])
        got = _run(model, params, state, x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestMultiOutputModel:
    def _two_head_json(self):
        return {"class_name": "Model", "config": {
            "name": "two_head",
            "layers": [
                {"class_name": "InputLayer", "name": "in1",
                 "config": {"batch_input_shape": [None, 6], "name": "in1"},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "trunk",
                 "config": {"output_dim": 8, "activation": "relu",
                            "name": "trunk"},
                 "inbound_nodes": [[["in1", 0, 0]]]},
                {"class_name": "Dense", "name": "head_a",
                 "config": {"output_dim": 3, "activation": "linear",
                            "name": "head_a"},
                 "inbound_nodes": [[["trunk", 0, 0]]]},
                {"class_name": "Dense", "name": "head_b",
                 "config": {"output_dim": 1, "activation": "linear",
                            "name": "head_b"},
                 "inbound_nodes": [[["trunk", 0, 0]]]},
            ],
            "input_layers": [["in1", 0, 0]],
            "output_layers": [["head_a", 0, 0], ["head_b", 0, 0]],
        }}

    def test_two_output_model_converts_and_fits(self):
        """VERDICT round-3 'done' criterion: a two-output functional Model
        converts and BOTH heads train through fit()."""
        model = model_from_json_config(self._two_head_json())
        rs = RS(15)
        n = 64
        x = rs.randn(n, 6).astype(np.float32)
        ya = rs.randn(n, 3).astype(np.float32)
        yb = rs.randn(n, 1).astype(np.float32)

        model.compile(optimizer="sgd", loss=["mse", "mse"])
        params0, _, _ = model.build(jax.random.PRNGKey(0), (16, 6))
        before_a = np.asarray(params0["head_a"]["weight"]).copy()
        before_b = np.asarray(params0["head_b"]["weight"]).copy()
        model.fit(x, [ya, yb], batch_size=16, nb_epoch=2)
        after_a = np.asarray(model.params["head_a"]["weight"])
        after_b = np.asarray(model.params["head_b"]["weight"])
        assert not np.allclose(before_a, after_a)
        assert not np.allclose(before_b, after_b)

        # evaluate: summed ParallelCriterion loss over both heads
        res = model.evaluate(x, (ya, yb), batch_size=16)
        assert res and np.isfinite(res[0][1])

    def test_single_loss_repeats_across_heads(self):
        model = model_from_json_config(self._two_head_json())
        model.compile(optimizer="sgd", loss="mse")
        from bigdl_tpu.nn.criterion import ParallelCriterion
        assert isinstance(model.criterion, ParallelCriterion)
        assert len(model.criterion.criteria) == 2

    def test_loss_count_mismatch_raises(self):
        model = model_from_json_config(self._two_head_json())
        with pytest.raises(ValueError, match="losses for"):
            model.compile(optimizer="sgd", loss=["mse", "mse", "mse"])

    def test_per_tensor_metrics_route_per_output(self):
        # round-5: per-tensor metrics on multi-output Models are ROUTED
        # per head (PerOutput wrapper) instead of rejected — the keras-1
        # flat-list form replicates across every output
        # (tests/test_keras_multi_metrics.py covers the full matrix)
        from bigdl_tpu.optim.validation import PerOutput

        model = model_from_json_config(self._two_head_json())
        model.compile(optimizer="sgd", loss=["mse", "mse"],
                      metrics=["top1"])
        assert [m.name for m in model.metrics] == \
            ["Top1Accuracy[out0]", "Top1Accuracy[out1]"]
        assert all(isinstance(m, PerOutput) for m in model.metrics)


class TestWrapperZooFixtureModel:
    def test_fixture_model_loads_json_and_weights(self):
        """The VERDICT fixture: one Sequential containing the whole
        previously-unimportable wrapper zoo loads definition + weights and
        the end-to-end forward matches a straight composition of the
        per-layer oracle math (each conversion is itself differentially
        tested above)."""
        h = w = 8
        cin = 2
        cfg = {"class_name": "Sequential", "config": [
            {"class_name": "AtrousConvolution2D",
             "config": {"nb_filter": 3, "nb_row": 3, "nb_col": 3,
                        "activation": "linear", "atrous_rate": [1, 1],
                        "batch_input_shape": [None, h, w, cin],
                        "name": "atrous"}},
            {"class_name": "SeparableConvolution2D",
             "config": {"nb_filter": 4, "nb_row": 3, "nb_col": 3,
                        "activation": "linear", "border_mode": "valid",
                        "depth_multiplier": 2, "name": "sep"}},
            {"class_name": "SReLU", "config": {"name": "srelu"}},
            {"class_name": "LocallyConnected2D",
             "config": {"nb_filter": 2, "nb_row": 2, "nb_col": 2,
                        "activation": "linear", "name": "lc2d"}},
            {"class_name": "Flatten", "config": {"name": "flat"}},
            {"class_name": "MaxoutDense",
             "config": {"output_dim": 6, "nb_feature": 3, "name": "mx"}},
            {"class_name": "Highway",
             "config": {"activation": "tanh", "name": "hwy"}},
            {"class_name": "RepeatVector", "config": {"n": 5, "name": "rv"}},
            {"class_name": "Bidirectional",
             "config": {"merge_mode": "concat", "name": "bi",
                        "layer": {"class_name": "LSTM",
                                  "config": {"output_dim": 4,
                                             "return_sequences": True,
                                             "activation": "tanh",
                                             "inner_activation": "sigmoid",
                                             "name": "lstm"}}}},
            {"class_name": "TimeDistributed",
             "config": {"name": "td",
                        "layer": {"class_name": "Dense",
                                  "config": {"output_dim": 3,
                                             "activation": "linear",
                                             "name": "d"}}}},
        ]}
        model = model_from_json_config(json.dumps(cfg))
        params, state, _ = model.build(jax.random.PRNGKey(0), (2, h, w, cin))

        rs = RS(14)

        def r(*shape):
            return (rs.randn(*shape) * 0.3).astype(np.float32)

        oh = ow = h - 2  # after two valid 3x3 convs: 8->6->4; lc2d 4->3
        srelu_shape = (h - 4, w - 4, 4)
        flat = 3 * 3 * 2
        lw = [
            [r(3, 3, cin, 3), r(3)],                       # atrous
            [r(3, 3, 3, 2), r(1, 1, 6, 4), r(4)],          # separable
            [r(*srelu_shape), r(*srelu_shape),
             r(*srelu_shape) + 1.0, r(*srelu_shape)],      # srelu
            [r(3 * 3, 2 * 2 * 4, 2), r(3, 3, 2)],          # lc2d
            [r(3, flat, 6), r(3, 6)],                      # maxout
            [r(6, 6), r(6, 6), r(6), r(6)],                # highway
            [r(6, 4), r(4, 4), r(4)] * 4                   # bi fwd lstm
            + [r(6, 4), r(4, 4), r(4)] * 4,                # bi bwd lstm
            [r(8, 3), r(3)],                               # td dense
        ]
        params, state = interop.import_keras_weights(model, params, state,
                                                     lw)
        x = rs.randn(2, h, w, cin).astype(np.float32)
        y = _run(model, params, state, x)
        assert y.shape == (2, 5, 3)
        assert np.isfinite(y).all()
        # spot-check placements: maxout kernel packed (in, k*out)
        mx = model.children["5"]
        assert np.asarray(
            params["5"]["0"]["weight"]).shape == (flat, 3 * 6)
        assert mx is not None
        # srelu params landed under their own names
        assert np.asarray(params["2"]["t_right"]).shape == srelu_shape
        np.testing.assert_allclose(np.asarray(params["2"]["t_left"]),
                                   lw[2][0])
