"""bigdl_tpu.fleet: multi-tenant front door (ISSUE 11).

The acceptance-criteria tests live here:

  * fair share under asymmetric load — a flooding tenant cannot starve
    a peer (starvation bound asserted on the dispatch log);
  * strict deadline-tier ordering — interactive dispatches before batch
    regardless of arrival order;
  * autoscaler grow/retire hysteresis on scripted signal sequences,
    including the steady-recompile-alarm retire veto;
  * warm scale-out through the process-scoped compilecache live layer
    (`fleet/warmup_reused` > 0, zero steady-state recompile alarms);
  * replica kill mid-flight: zero ACCEPTED requests lost — every future
    settles with a result or a loud error, never hangs.

Scheduler/router mechanics run against fake runtimes (no device work,
so the ordering assertions are exact); the scale-out and kill-burst
tests run real ServingRuntimes on the CPU backend.
"""

import threading
import time

import numpy as np
import pytest

import jax

import bigdl_tpu.compilecache as cc
import bigdl_tpu.nn as nn
from bigdl_tpu import obs
from bigdl_tpu.fleet import (
    AutoscalerConfig,
    FairShareScheduler,
    FleetAutoscaler,
    FleetRouter,
    TenantConfig,
    TenantQueue,
)
from bigdl_tpu.fleet.tenancy import FleetRequest
from bigdl_tpu.obs.metrics import MetricsRegistry, prom_series
from bigdl_tpu.resilience import ReplicaKillFault
from bigdl_tpu.serving import ServingRuntime
from bigdl_tpu.serving.batcher import (
    DeadlineExceeded,
    Rejected,
    ServingClosed,
    _Future,
)
from bigdl_tpu.serving.metrics import ServingMetrics


@pytest.fixture()
def fresh_registry():
    old = obs.set_registry(MetricsRegistry())
    try:
        yield obs.registry()
    finally:
        obs.set_registry(old)


@pytest.fixture()
def cache_root(tmp_path):
    root = str(tmp_path / "cc")
    cc.set_cache_dir(root)
    try:
        yield root
    finally:
        cc.reset()


@pytest.fixture(scope="module")
def small_model():
    model = nn.Sequential(nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 4))
    params, state, _ = model.build(jax.random.PRNGKey(0), (8, 6))
    return model, params, state


def _row(seed=0):
    return np.random.RandomState(seed).rand(1, 6).astype(np.float32)


# -- fake runtimes (exact scheduler-order assertions, no device work) ------


class EchoRuntime:
    """Settles every request immediately with its input."""

    def __init__(self):
        self.submitted = []

    def submit(self, x, deadline_ms=None):
        fut = _Future()
        self.submitted.append(x)
        fut.set_result(x)
        return fut

    def close(self, drain=True, timeout=None):
        pass


class ManualRuntime:
    """Holds every request open until the test releases (or closes) it —
    the stand-in for a replica with work in flight."""

    def __init__(self):
        self._lock = threading.Lock()
        self.pending = []

    def submit(self, x, deadline_ms=None):
        fut = _Future()
        with self._lock:
            self.pending.append((x, fut))
        return fut

    def n_pending(self):
        with self._lock:
            return len(self.pending)

    def release_all(self):
        with self._lock:
            pend, self.pending = self.pending, []
        for x, fut in pend:
            fut.set_result(x)

    def close(self, drain=True, timeout=None):
        with self._lock:
            pend, self.pending = self.pending, []
        for _, fut in pend:
            if not fut.done():
                fut.set_error(ServingClosed("runtime shut down"))


def _wait_until(cond, timeout=10.0, msg="condition"):
    deadline = time.time() + timeout
    while not cond():
        if time.time() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        time.sleep(0.005)


# -- _Future done-callbacks (the completion-chaining primitive) ------------


def test_future_callback_after_settle_fires_inline():
    fut = _Future()
    fut.set_result(41)
    seen = []
    fut.add_done_callback(lambda f: seen.append(f.result(0)))
    assert seen == [41]


def test_future_callback_fires_exactly_once():
    fut = _Future()
    seen = []
    fut.add_done_callback(lambda f: seen.append("cb"))
    fut.set_error(RuntimeError("first wins"))
    fut.set_result("late overwrite")  # must not re-fire
    assert seen == ["cb"]
    assert isinstance(fut.error(), RuntimeError)


# -- deficit-weighted fair share (pure scheduler) --------------------------


def _queue(name, tier="batch", weight=1.0, n_reqs=0, rows=1):
    q = TenantQueue(TenantConfig(name, tier=tier, weight=weight,
                                 capacity=max(256, n_reqs)))
    for _ in range(n_reqs):
        q.admit(FleetRequest(name, None, rows, None))
    return q


def test_drr_strict_tier_priority(fresh_registry):
    sched = FairShareScheduler(quantum_rows=4)
    interactive = _queue("i", tier="interactive", n_reqs=2)
    batch = _queue("b", tier="batch", n_reqs=50)
    best = _queue("e", tier="best_effort", n_reqs=50)
    order = []
    while len(interactive) or len(batch):
        q = sched.pick_next([interactive, batch, best])
        order.append(q.name)
        q.pop()
    # every interactive request dispatched before ANY batch request,
    # every batch before any best-effort
    assert order[:2] == ["i", "i"]
    assert "e" not in order


def test_drr_weight_sets_dispatch_ratio(fresh_registry):
    sched = FairShareScheduler(quantum_rows=1)
    heavy = _queue("heavy", weight=3.0, n_reqs=60)
    light = _queue("light", weight=1.0, n_reqs=60)
    picks = [sched.pick_next([heavy, light]).pop().tenant for _ in range(40)]
    ratio = picks.count("heavy") / picks.count("light")
    assert 2.0 < ratio < 4.5, f"expected ~3:1 dispatch ratio, got {ratio}"


def test_drr_starvation_bound_under_flood(fresh_registry):
    quantum = 8
    sched = FairShareScheduler(quantum_rows=quantum)
    flood = _queue("flood", n_reqs=200)
    victim = _queue("victim", n_reqs=1)
    picks = []
    for _ in range(3 * quantum):
        picks.append(sched.pick_next([flood, victim]).pop().tenant)
        if "victim" in picks:
            break
    # equal weights: the victim's head request dispatches within one
    # quantum round of the flood, never later
    assert "victim" in picks
    assert picks.index("victim") <= quantum + 1


def test_drr_deficit_resets_when_queue_empties(fresh_registry):
    q = _queue("t", n_reqs=1)
    q.deficit = 999.0
    q.pop()
    assert q.deficit == 0.0  # no banking credit while idle


# -- router over fake runtimes ---------------------------------------------


def _echo_router(tenants, **kw):
    runtimes = {}

    def factory(name):
        rt = EchoRuntime()
        runtimes[name] = rt
        return rt

    kw.setdefault("n_replicas", 1)
    router = FleetRouter(factory, tenants=tenants, **kw)
    return router, runtimes


def test_tier_preemption_ordering(fresh_registry):
    router, _ = _echo_router(
        [TenantConfig("bulk", tier="batch"),
         TenantConfig("chat", tier="interactive")])
    try:
        router.pause()
        futs = [router.submit("bulk", _row(), deadline_ms=60_000)
                for _ in range(5)]
        futs += [router.submit("chat", _row(), deadline_ms=60_000)
                 for _ in range(5)]
        router.resume()
        for f in futs:
            f.result(10)
        tenants_in_order = [t for t, _, _ in router.dispatch_log]
        # batch arrived FIRST, but interactive's strict priority wins
        assert tenants_in_order[:5] == ["chat"] * 5
        assert tenants_in_order[5:] == ["bulk"] * 5
    finally:
        router.close()


def test_fair_share_bounds_starvation_in_dispatch_log(fresh_registry):
    quantum = 4
    router, _ = _echo_router(
        [TenantConfig("flood", tier="batch"),
         TenantConfig("victim", tier="batch")],
        quantum_rows=quantum)
    try:
        router.pause()
        futs = [router.submit("flood", _row(), deadline_ms=60_000)
                for _ in range(30)]
        futs += [router.submit("victim", _row(), deadline_ms=60_000)
                 for _ in range(3)]
        router.resume()
        for f in futs:
            f.result(10)
        tenants_in_order = [t for t, _, _ in router.dispatch_log]
        first_victim = tenants_in_order.index("victim")
        assert first_victim <= quantum + 1, (
            f"victim starved for {first_victim} dispatches under the flood")
    finally:
        router.close()


def test_tenant_queue_full_rejects_loudly(fresh_registry):
    router, _ = _echo_router([TenantConfig("t", capacity=2)])
    try:
        router.pause()
        router.submit("t", _row(), deadline_ms=60_000)
        router.submit("t", _row(), deadline_ms=60_000)
        with pytest.raises(Rejected):
            router.submit("t", _row(), deadline_ms=60_000)
        assert fresh_registry.get("serving/rejected_queue_full|tenant=t") == 1
    finally:
        router.resume()
        router.close()


def test_unknown_tenant_raises(fresh_registry):
    router, _ = _echo_router([TenantConfig("t")])
    try:
        with pytest.raises(KeyError):
            router.submit("nobody", _row())
    finally:
        router.close()


def test_deadline_expires_in_fleet_queue(fresh_registry):
    router, _ = _echo_router([TenantConfig("t", tier="interactive")])
    try:
        router.pause()  # nothing dispatches; the deadline must still fire
        fut = router.submit("t", _row(), deadline_ms=30)
        _wait_until(fut.done, 5, "deadline expiry")
        with pytest.raises(DeadlineExceeded):
            fut.result(0)
        assert fresh_registry.get("serving/rejected_deadline|tenant=t") == 1
    finally:
        router.resume()
        router.close()


def test_close_rejects_new_and_drains_accepted(fresh_registry):
    router, _ = _echo_router([TenantConfig("t")])
    futs = [router.submit("t", _row(), deadline_ms=60_000) for _ in range(4)]
    router.close(drain=True)
    for f in futs:  # accepted work completed through the drain
        assert f.result(0).shape == (1, 6)
    with pytest.raises(ServingClosed):
        router.submit("t", _row())


def test_close_no_drain_fails_queued_loudly(fresh_registry):
    router, _ = _echo_router([TenantConfig("t")])
    router.pause()
    futs = [router.submit("t", _row(), deadline_ms=60_000) for _ in range(4)]
    router.close(drain=False)
    for f in futs:
        assert isinstance(f.error(), ServingClosed)  # loud, not lost


def test_kill_replica_redispatches_inflight_zero_lost(fresh_registry):
    runtimes = {}

    def factory(name):
        rt = ManualRuntime()
        runtimes[name] = rt
        return rt

    router = FleetRouter(factory, n_replicas=2, tenants=[TenantConfig("t")])
    try:
        futs = [router.submit("t", _row(i), deadline_ms=60_000)
                for i in range(10)]
        _wait_until(
            lambda: sum(rt.n_pending() for rt in runtimes.values()) == 10,
            msg="all 10 requests dispatched")
        victim_name = max(runtimes, key=lambda n: runtimes[n].n_pending())
        n_inflight = runtimes[victim_name].n_pending()
        assert n_inflight > 0
        assert router.kill_replica(victim_name) == victim_name
        survivor = next(rt for n, rt in runtimes.items() if n != victim_name)
        # every request the victim held redispatches to the survivor
        _wait_until(lambda: survivor.n_pending() == 10,
                    msg="redistribution to the survivor")
        survivor.release_all()
        for f in futs:  # zero accepted requests lost
            assert f.result(10).shape == (1, 6)
        snap = router.snapshot()
        assert snap["redispatched"] >= n_inflight
        assert snap["replica_kills"] == 1
        completed = {f.meta["replica"] for f in futs}
        assert completed == {next(n for n in runtimes if n != victim_name)}
    finally:
        router.close()


def test_redispatch_budget_exhaustion_is_loud(fresh_registry):
    """With every replica dying, an accepted request fails with a loud
    Rejected after max_redispatch bounces — never a silent hang."""
    runtimes = {}

    def factory(name):
        rt = ManualRuntime()
        runtimes[name] = rt
        return rt

    router = FleetRouter(factory, n_replicas=2, tenants=[TenantConfig("t")],
                         max_redispatch=2)
    try:
        fut = router.submit("t", _row(), deadline_ms=60_000)
        for _ in range(3):
            _wait_until(
                lambda: any(rt.n_pending() for rt in runtimes.values())
                or fut.done(), msg="dispatch or settle")
            if fut.done():
                break
            router.add_replica()  # keep a landing spot for the redispatch
            victim = next(n for n in runtimes if runtimes[n].n_pending())
            router.kill_replica(victim)
        _wait_until(fut.done, 10, "loud failure")
        assert isinstance(fut.error(), Rejected)
        assert fresh_registry.get("serving/rejected_replica_lost|tenant=t") == 1
    finally:
        router.close(drain=False)


# -- autoscaler hysteresis (scripted signals) ------------------------------


class FakeFleet:
    def __init__(self, n=1):
        self.n = n
        self.events = []

    def n_replicas(self):
        return self.n

    def add_replica(self):
        self.n += 1
        self.events.append("grow")
        return f"r{self.n}"

    def retire_replica(self, name=None, timeout=None):
        if self.n <= 1:
            return None
        self.n -= 1
        self.events.append("shrink")
        return f"r{self.n + 1}"


def _autoscaler(fleet, signals, **cfg_kw):
    cfg_kw.setdefault("min_replicas", 1)
    cfg_kw.setdefault("max_replicas", 3)
    cfg_kw.setdefault("grow_after", 3)
    cfg_kw.setdefault("shrink_after", 3)
    cfg_kw.setdefault("cooldown_ticks", 2)
    cfg_kw.setdefault("high_queue_depth", 10)
    cfg_kw.setdefault("high_p99_ms", 500.0)
    cfg_kw.setdefault("low_queue_depth", 1)
    it = iter(signals)
    return FleetAutoscaler(fleet, AutoscalerConfig(**cfg_kw),
                           signals_fn=lambda: next(it))


def _sig(depth=0.0, p99=0.0, alarms=0.0):
    return {"queue_depth": depth, "p99_ms": p99, "recompile_alarms": alarms}


def test_autoscaler_grows_after_consecutive_high_ticks(fresh_registry):
    fleet = FakeFleet(1)
    auto = _autoscaler(fleet, [_sig(depth=50)] * 6)
    decisions = [auto.tick() for _ in range(6)]
    # 2 high ticks hold; the 3rd grows; the action resets the streak and
    # starts the cooldown, so the NEXT grow needs 3 more high ticks
    assert decisions == ["hold", "hold", "grow", "hold", "hold", "grow"]
    assert fleet.n == 3


def test_autoscaler_oscillation_holds(fresh_registry):
    fleet = FakeFleet(1)
    sigs = [_sig(depth=50), _sig(depth=50), _sig(depth=5),  # neutral resets
            _sig(depth=50), _sig(depth=50), _sig(depth=5)]
    auto = _autoscaler(fleet, sigs)
    decisions = [auto.tick() for _ in range(6)]
    assert "grow" not in decisions
    assert fleet.n == 1


def test_autoscaler_retires_after_low_streak_with_cooldown(fresh_registry):
    fleet = FakeFleet(3)
    auto = _autoscaler(fleet, [_sig(depth=0)] * 10)
    decisions = [auto.tick() for _ in range(10)]
    # shrink every (streak-rebuild) 3 ticks until min_replicas, then hold
    assert decisions[:6] == ["hold", "hold", "shrink", "hold", "hold",
                             "shrink"]
    assert fleet.n == 1
    assert "shrink" not in decisions[6:]  # at min_replicas: never below


def test_autoscaler_alarm_vetoes_retire(fresh_registry):
    fleet = FakeFleet(2)
    # low load, but the steady-recompile alarm count keeps climbing
    sigs = [_sig(depth=0, alarms=float(i)) for i in range(6)]
    auto = _autoscaler(fleet, sigs)
    decisions = [auto.tick() for _ in range(6)]
    assert "shrink" not in decisions
    assert "veto" in decisions
    assert fleet.n == 2


def test_autoscaler_respects_max_replicas(fresh_registry):
    fleet = FakeFleet(3)
    auto = _autoscaler(fleet, [_sig(depth=50)] * 5, max_replicas=3)
    decisions = [auto.tick() for _ in range(5)]
    assert "grow" not in decisions


def test_autoscaler_grows_on_slo_burn_rate(fresh_registry):
    # queue shallow and p99 healthy, but a tenant is burning its error
    # budget: the burn-rate gauge alone must drive the grow streak
    fleet = FakeFleet(1)
    sigs = [dict(_sig(depth=0, p99=0), slo_burn_rate=20.0)] * 3
    auto = _autoscaler(fleet, sigs)
    decisions = [auto.tick() for _ in range(3)]
    assert decisions == ["hold", "hold", "grow"]
    assert fleet.n == 2


def test_autoscaler_burn_blocks_retire(fresh_registry):
    # a burning tenant is never "low load" no matter how empty the queue
    fleet = FakeFleet(2)
    sigs = [dict(_sig(depth=0, p99=0), slo_burn_rate=20.0)] * 8
    auto = _autoscaler(fleet, sigs, max_replicas=2)
    decisions = [auto.tick() for _ in range(8)]
    assert "shrink" not in decisions
    assert fleet.n == 2


def test_slo_monitor_over_router_feeds_autoscaler_signal(fresh_registry):
    from bigdl_tpu.obs import SLOObjective, SloMonitor

    router = FleetRouter(lambda name: EchoRuntime(), n_replicas=1,
                         tenants=[TenantConfig("t")])
    try:
        assert router.tenant_metrics("nope") is None
        m = router.tenant_metrics("t")
        assert isinstance(m, ServingMetrics)
        mon = SloMonitor([SLOObjective("t", p99_ms=50.0)],
                         source=router.tenant_metrics,
                         registry_fn=obs.registry)
        mon.tick(now=0.0)
        # a latency cliff on the live tenant metrics
        for _ in range(20):
            m.on_complete(queue_ms=1.0, total_ms=500.0, depth=0)
        out = mon.tick(now=10.0)
        assert out["t"]["alerts"], out
        assert fresh_registry.get("slo/alerts_total|tenant=t") == 1
        # ...surfaces through the autoscaler's live signal closure
        auto = FleetAutoscaler(router, AutoscalerConfig(
            min_replicas=1, max_replicas=2, grow_after=1, shrink_after=99,
            cooldown_ticks=0, high_queue_depth=1e9, high_p99_ms=1e9))
        sig = auto._default_signals()
        assert sig["slo_burn_rate"] >= auto.config.high_burn_rate
        assert auto.tick() == "grow"
        assert router.n_replicas() == 2
    finally:
        router.close()


# -- Prometheus tenant label dimension -------------------------------------


def test_prom_series_renders_label_suffix():
    assert prom_series("serving/p99|tenant=acme") == (
        "bigdl_tpu_serving_p99", '{tenant="acme"}')
    assert prom_series("serving/p99") == ("bigdl_tpu_serving_p99", "")
    name, labels = prom_series('a/b|k=va"lue,tier=x')
    assert labels == '{k="va\\"lue",tier="x"}'


def test_prometheus_exports_per_tenant_series(fresh_registry, tmp_path):
    ServingMetrics(tenant="acme").on_admit(1)
    ServingMetrics(tenant="bulk").on_admit(1)
    ServingMetrics().on_admit(1)  # unlabeled series coexists
    path = str(tmp_path / "metrics.prom")
    fresh_registry.export_prometheus(path)
    text = open(path).read()
    assert 'bigdl_tpu_serving_requests_admitted{tenant="acme"} 1' in text
    assert 'bigdl_tpu_serving_requests_admitted{tenant="bulk"} 1' in text
    assert "\nbigdl_tpu_serving_requests_admitted 1" in text
    # one TYPE line per metric family, labels notwithstanding
    assert text.count("# TYPE bigdl_tpu_serving_requests_admitted counter") == 1


# -- real runtimes: warm scale-out + chaos kill under burst ----------------


def _serving_factory(small_model):
    model, params, state = small_model

    def factory(name):
        return ServingRuntime(model, params, state, buckets=(1, 8),
                              max_wait_ms=1.0,
                              example_input=np.zeros((1, 6), np.float32))

    return factory


def test_warm_scaleout_reuses_cache_no_steady_recompiles(
        small_model, fresh_registry, cache_root):
    obs.set_observability(metrics=True, compile_monitor=True)
    router = FleetRouter(_serving_factory(small_model), n_replicas=1,
                         tenants=[TenantConfig("t")])
    try:
        assert router.predict("t", _row(), deadline_ms=30_000,
                              timeout=30).shape == (1, 4)
        hits_before = fresh_registry.get("compile/cache_hits")
        router.add_replica()  # scale-out: must warm from the live layer
        assert fresh_registry.get("compile/cache_hits") > hits_before
        assert fresh_registry.get("fleet/warmup_reused") > 0
        # zero steady-state recompiles: scale-out compiled NOTHING anew
        assert fresh_registry.get("compile/steady_recompiles") == 0
        assert router.predict("t", _row(1), deadline_ms=30_000,
                              timeout=30).shape == (1, 4)
    finally:
        router.close()


def test_replica_kill_mid_burst_zero_lost(small_model, fresh_registry,
                                          cache_root):
    """The chaos lane acceptance bar: SIGKILL-analog drop of one replica
    mid-burst; every ACCEPTED request settles with a result or a loud
    deadline/rejection error — silently dropped is not an ending."""
    router = FleetRouter(_serving_factory(small_model), n_replicas=2,
                         tenants=[TenantConfig("bulk", tier="batch"),
                                  TenantConfig("chat", tier="interactive")])
    fault = ReplicaKillFault(at_dispatch=5)
    router.set_chaos(fault)
    try:
        futs = []
        for i in range(24):
            tenant = "chat" if i % 3 == 0 else "bulk"
            futs.append(router.submit(tenant, _row(i), deadline_ms=60_000))
        settled = [f.result(60) for f in futs]
        assert len(settled) == len(futs)  # zero lost, zero hung
        assert all(o.shape == (1, 4) for o in settled)
        assert len(fault.fired) == 1
        snap = router.snapshot()
        assert snap["replica_kills"] == 1
        done = sum(snap["tenants"][t]["requests_completed"]
                   for t in ("bulk", "chat"))
        assert done == len(futs)
    finally:
        router.close()


def test_kill_mid_burst_stitched_trace_one_cid_one_bundle(
        small_model, fresh_registry, cache_root, tmp_path):
    """The flight-recorder acceptance bar: a replica dies mid-burst and
    the black box yields (a) exactly ONE postmortem bundle naming the
    trigger, (b) a stitched trace whose flow chain follows the bounced
    request admit -> dispatch(A) -> redispatch -> dispatch(B) ->
    complete across lanes, and (c) ONE cid on the future across the
    redispatch, counted per tenant."""
    import json
    import os

    flight_dir = str(tmp_path / "flight")
    # fresh compile monitor: signatures settled by earlier tests must not
    # classify THIS test's warmup compiles as steady recompiles
    obs.set_observability(tracing=True, compile_monitor=True,
                          flight=True, flight_dir=flight_dir)
    router = FleetRouter(_serving_factory(small_model), n_replicas=2,
                         tenants=[TenantConfig("bulk", tier="batch"),
                                  TenantConfig("chat", tier="interactive")])
    router.set_chaos(ReplicaKillFault(at_dispatch=5))
    try:
        before = {t.name for t in threading.enumerate()
                  if not t.name.startswith("fleet-reaper")}
        futs = []
        for i in range(24):
            tenant = "chat" if i % 3 == 0 else "bulk"
            futs.append(router.submit(tenant, _row(i), deadline_ms=60_000))
        assert all(f.result(60).shape == (1, 4) for f in futs)

        # ONE cid per request, held across the redispatch
        cids = [f.meta["cid"] for f in futs]
        assert len(set(cids)) == len(futs)
        bounced = [f for f in futs if f.meta["attempts"] > 1]
        assert bounced, "the kill must strand at least one request"
        n_redis = sum(
            fresh_registry.get(f"fleet/redispatches|tenant={t}")
            for t in ("bulk", "chat"))
        assert n_redis == fresh_registry.get("fleet/redispatched") > 0

        # the bounced cid's timeline names both replicas
        cid = bounced[0].meta["cid"]
        tl = obs.request_timeline(cid)
        assert tl["redispatches"] >= 1
        assert len(set(tl["replicas"])) == 2
        hop_names = [h["name"] for h in tl["hops"]]
        for expected in ("fleet.admit", "fleet.dispatch", "fleet.redispatch",
                         "fleet.complete"):
            assert expected in hop_names, hop_names

        # stitched trace: valid JSON, replica lanes, cross-lane flow
        doc = obs.export_fleet_trace(str(tmp_path / "fleet_trace.json"))
        with open(tmp_path / "fleet_trace.json") as f:
            assert json.load(f) == doc
        lanes = doc["otherData"]["replica_lanes"]
        assert sum(1 for n in lanes.values()
                   if n.startswith("replica:")) == 2
        flow = [e for e in doc["traceEvents"]
                if e.get("id") == cid and e["name"] == "fleet.request"]
        assert [e["ph"] for e in flow] == \
            ["s"] + ["t"] * (len(flow) - 2) + ["f"]
        assert len({e["pid"] for e in flow}) >= 2  # crosses lanes

        # exactly ONE bundle for the death (dedup ate the per-request
        # bounces), and its trace round-trips as JSON
        bundles = [d for d in os.listdir(flight_dir)
                   if "fleet_replica_death" in d]
        assert len(bundles) == 1
        with open(os.path.join(flight_dir, bundles[0],
                               "MANIFEST.json")) as f:
            assert json.load(f)["reason"] == "fleet.replica_death"
        with open(os.path.join(flight_dir, bundles[0], "trace.json")) as f:
            assert json.load(f)["traceEvents"]
    finally:
        router.close()
        obs.set_observability(tracing=False, flight=False)
    # no thread leaks: the recorder and stitcher added zero threads
    _wait_until(lambda: {t.name for t in threading.enumerate()
                         if not t.name.startswith("fleet-reaper")} <= before,
                msg="fleet threads torn down")


def test_routed_output_bitwise_equals_direct(small_model, fresh_registry):
    """The front door adds scheduling, not numerics: routed output is
    BITWISE the direct runtime's output."""
    model, params, state = small_model
    x = _row(7)
    direct = ServingRuntime(model, params, state, buckets=(1, 8),
                            max_wait_ms=1.0,
                            example_input=np.zeros((1, 6), np.float32))
    try:
        want = np.asarray(direct.predict(x))
    finally:
        direct.close()
    router = FleetRouter(_serving_factory(small_model), n_replicas=1,
                         tenants=[TenantConfig("t")])
    try:
        got = np.asarray(router.predict("t", x, deadline_ms=30_000,
                                        timeout=30))
        np.testing.assert_array_equal(want, got)
    finally:
        router.close()
