"""Test configuration: force an 8-virtual-device CPU platform so multi-chip
sharding paths are exercised in one process — the analogue of the reference
testing its BlockManager allreduce with SparkContext("local[N]") (survey §4).

Note: the environment's sitecustomize registers and initializes the real
TPU backend at interpreter startup, BEFORE this conftest runs — so setting
env vars is not enough; we must also clear the already-initialized backends
and switch the platform config to cpu.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    import jax.extend.backend as _jeb

    _jeb.clear_backends()
except Exception:  # pragma: no cover - fallback for older jax
    import jax._src.xla_bridge as _xb

    _xb._clear_backends()

assert jax.device_count() == 8, (
    f"tests need the 8-virtual-device CPU mesh, got {jax.devices()}")

# Full-precision matmuls for differential tests against torch CPU (on TPU the
# framework default stays at the fast bf16-pass precision).
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)


def pytest_configure(config):
    # two-tier test strategy (the reference tag-splits integration tests,
    # spark/dl/pom.xml:327-341): the quick tier is `pytest -m "not slow"`
    # (<2 min); the full tier runs everything
    config.addinivalue_line(
        "markers",
        "slow: heavyweight tier — differential oracles, trainer loops, "
        "registry-wide sweeps; deselect with -m \"not slow\"")
