"""Test configuration: force an 8-virtual-device CPU platform so multi-chip
sharding paths are exercised in one process — the analogue of the reference
testing its BlockManager allreduce with SparkContext("local[N]") (survey §4).

Note: the environment's sitecustomize registers and initializes the real
TPU backend at interpreter startup, BEFORE this conftest runs — so setting
env vars is not enough; we must also clear the already-initialized backends
and switch the platform config to cpu.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    import jax.extend.backend as _jeb

    _jeb.clear_backends()
except Exception:  # pragma: no cover - fallback for older jax
    import jax._src.xla_bridge as _xb

    _xb._clear_backends()

assert jax.device_count() == 8, (
    f"tests need the 8-virtual-device CPU mesh, got {jax.devices()}")

# Full-precision matmuls for differential tests against torch CPU (on TPU the
# framework default stays at the fast bf16-pass precision).
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture()
def strict_transfers():
    """Run the test body under jax.transfer_guard("disallow"): any implicit
    h2d transfer (e.g. a Python scalar canonicalized into a jitted call)
    raises instead of silently syncing.  See docs/analysis.md for the
    h2d/d2h asymmetry — d2h pulls need the static linter."""
    from bigdl_tpu.analysis.runtime import strict_transfers as _guard

    with _guard(True):
        yield


@pytest.fixture(autouse=True)
def _lockdep_reset():
    """Lockdep state is process-global (edges, violations, counters) and
    its instrumentation patches `threading.Lock`/`RLock` — a test that
    instruments and fails before restoring would silently observe every
    later test.  Restore the factories and drop collected state after
    each test that touched the sanitizer; tests that never import it pay
    one sys.modules dict hit."""
    yield
    mod = sys.modules.get("bigdl_tpu.analysis.lockdep")
    if mod is not None:
        mod.uninstrument_locks()
        mod.reset()


@pytest.fixture(autouse=True)
def _thread_leak_guard():
    """No worker thread OR reader process may survive a test: a DeviceFeed
    thread (or any new non-daemon thread) still alive after the test body
    means a close() path is broken — the class of leak that deadlocks
    interpreter exit or poisons the next test's timing — and an orphaned
    reader child (dataset/readers.py worker) keeps assembling batches
    into a dead pipe forever.  Pre-existing threads (pytest's own, library
    pools started at import) and pre-existing children are exempt via the
    snapshots."""
    import multiprocessing
    import threading
    import time

    before = set(threading.enumerate())
    procs_before = {p.pid for p in multiprocessing.active_children()}

    def offenders():
        return [t for t in threading.enumerate()
                if t not in before and t.is_alive()
                and (not t.daemon
                     or t.name.startswith(("DeviceFeed", "AsyncCkptWriter",
                                           "serving-batcher",
                                           "HealthWatchdog",
                                           "fleet-router",
                                           "fleet-autoscaler",
                                           "fleet-reaper",
                                           "fleet-complete")))]

    def child_offenders():
        # active_children() also reaps finished children; any new child
        # still alive past the grace period is a pool-shutdown bug
        return [p for p in multiprocessing.active_children()
                if p.pid not in procs_before and p.is_alive()]

    yield
    # grace for threads mid-shutdown (close() joins, but a worker that
    # observed the stop flag may need a scheduler tick to finish dying)
    deadline = time.time() + 2.0
    while (offenders() or child_offenders()) and time.time() < deadline:
        time.sleep(0.01)
    leaked = offenders()
    assert not leaked, (
        f"worker threads leaked past the test: "
        f"{[(t.name, t.daemon) for t in leaked]}")
    leaked_procs = child_offenders()
    assert not leaked_procs, (
        f"reader processes leaked past the test: "
        f"{[(p.name, p.pid) for p in leaked_procs]}")


def pytest_configure(config):
    # two-tier test strategy (the reference tag-splits integration tests,
    # spark/dl/pom.xml:327-341): the quick tier is `pytest -m "not slow"`
    # (<2 min); the full tier runs everything
    config.addinivalue_line(
        "markers",
        "slow: heavyweight tier — differential oracles, trainer loops, "
        "registry-wide sweeps; deselect with -m \"not slow\"")
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection tests (resilience subsystem); "
        "the CI quick tier runs them as their own lane")
