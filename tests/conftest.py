"""Test configuration: force an 8-virtual-device CPU platform BEFORE jax
backends initialize, so multi-chip sharding paths are exercised in one
process — the analogue of the reference testing its BlockManager allreduce
with SparkContext("local[N]") (survey §4)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# Full-precision matmuls for differential tests against torch CPU (on TPU the
# framework default stays at the fast bf16-pass precision).
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)
