"""Device-side sparse path: SparseLinear (ids, values) bags.

Reference capability: tensor/SparseTensor.scala + SparseTensorMath.scala
execute sparse gemm natively so wide features never densify.  The
TPU-native equivalent is a batched row gather + masked weighted reduce
over bags padded to a static nnz — parity-tested here against the dense
multi-hot path (forward AND gradients), end-to-end through the TFRecord
VarLen flow with encoding='bag'.
"""

import numpy as np

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.core.table import Table
from bigdl_tpu.dataset import VarLenFeature
from bigdl_tpu.dataset.minibatch import SparseMiniBatch, has_sparse_feature
from bigdl_tpu.dataset.sample import Sample, SparseBag, SparseFeature
from bigdl_tpu.dataset.tfrecord import ParsedExampleDataSet, TFRecordWriter
from bigdl_tpu.nn.tf_ops import build_example_proto
from bigdl_tpu.optim import DistriOptimizer, SGD, Trigger

import pytest

# heavyweight tier: differential oracles / trainers / registry sweeps;
# the quick tier is 'pytest -m "not slow"' (README Testing)
pytestmark = pytest.mark.slow


VOCAB, B, NNZ, OUT = 40, 6, 5, 3


def _random_bags(rs, vocab=VOCAB, b=B, nnz=NNZ):
    """(ids, vals) padded bags + the equivalent dense multi-hot batch."""
    ids = np.full((b, nnz), -1, np.int32)
    vals = np.zeros((b, nnz), np.float32)
    dense = np.zeros((b, vocab), np.float32)
    for r in range(b):
        k = rs.randint(1, nnz + 1)
        chosen = rs.choice(vocab, size=k, replace=False)
        w = rs.rand(k).astype(np.float32) + 0.5
        ids[r, :k] = chosen
        vals[r, :k] = w
        dense[r, chosen] = w
    return ids, vals, dense


class TestSparseLinearBag:
    def test_forward_parity_vs_dense(self):
        rs = np.random.RandomState(0)
        ids, vals, dense = _random_bags(rs)
        m = nn.SparseLinear(VOCAB, OUT)
        params, state, out_shape = m.build(jax.random.PRNGKey(0),
                                           Table((B, NNZ), (B, NNZ)))
        assert tuple(out_shape) == (B, OUT)
        y_bag, _ = m.apply(params, state, Table(jnp.asarray(ids),
                                                jnp.asarray(vals)))
        y_dense, _ = m.apply(params, state, jnp.asarray(dense))
        np.testing.assert_allclose(np.asarray(y_bag), np.asarray(y_dense),
                                   rtol=1e-5, atol=1e-5)
        # tuple input form works too (how SparseMiniBatch delivers it)
        y_tup, _ = m.apply(params, state, (jnp.asarray(ids),
                                           jnp.asarray(vals)))
        np.testing.assert_allclose(np.asarray(y_tup), np.asarray(y_bag))

    def test_gradient_parity_vs_dense(self):
        """d loss / d W through the gather path == through the dense
        multi-hot matmul (the VERDICT 'done' criterion)."""
        rs = np.random.RandomState(1)
        ids, vals, dense = _random_bags(rs)
        m = nn.SparseLinear(VOCAB, OUT)
        params, state, _ = m.build(jax.random.PRNGKey(1),
                                   Table((B, NNZ), (B, NNZ)))
        tgt = rs.randn(B, OUT).astype(np.float32)

        def loss_bag(p):
            y, _ = m.apply(p, state, Table(jnp.asarray(ids),
                                           jnp.asarray(vals)))
            return jnp.mean((y - tgt) ** 2)

        def loss_dense(p):
            y, _ = m.apply(p, state, jnp.asarray(dense))
            return jnp.mean((y - tgt) ** 2)

        g_bag = jax.grad(loss_bag)(params)
        g_dense = jax.grad(loss_dense)(params)
        np.testing.assert_allclose(np.asarray(g_bag["weight"]),
                                   np.asarray(g_dense["weight"]),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(g_bag["bias"]),
                                   np.asarray(g_dense["bias"]),
                                   rtol=1e-5, atol=1e-6)

    def test_grad_hlo_has_no_dense_vocab_product(self):
        """The backward pass must scale with nnz, not vocab: no
        (B, vocab)-shaped intermediate may appear in the compiled grad."""
        wide = 100_000
        m = nn.SparseLinear(wide, OUT)
        params, state, _ = m.build(jax.random.PRNGKey(0),
                                   Table((B, NNZ), (B, NNZ)))
        ids = jnp.zeros((B, NNZ), jnp.int32)
        vals = jnp.ones((B, NNZ), jnp.float32)

        def loss(p):
            y, _ = m.apply(p, state, Table(ids, vals))
            return jnp.sum(y)

        txt = jax.jit(jax.grad(loss)).lower(params).as_text()
        assert f"{B},{wide}" not in txt  # no densified one-hot batch


class TestSparseBagHost:
    def test_bag_from_sparse_feature(self):
        sf = SparseFeature(np.array([[2], [7]]), np.array([1.5, 2.5],
                                                          np.float32),
                           (VOCAB,))
        bag = sf.to_bag(4)
        np.testing.assert_array_equal(bag.ids, [2, 7, -1, -1])
        np.testing.assert_array_equal(bag.values, [1.5, 2.5, 0, 0])

    def test_empty_record_keeps_dtype(self):
        """A zero-id record must not flip the batch dtype (jit recompile
        hazard)."""
        full = SparseBag(np.array([3]), np.array([2], np.int64), 4)
        empty = SparseBag(np.array([], np.int64),
                          np.array([], np.int64), 4)
        assert empty.values.dtype == np.int64
        batch = SparseMiniBatch.from_samples(
            [Sample(full, np.int32(0)), Sample(empty, np.int32(1))])
        ids, vals = batch.input
        assert vals.dtype == np.int64
        assert ids.shape == (2, 4)

    def test_has_sparse_feature_sees_bags(self):
        s = Sample(SparseBag([1], [1.0], 3), np.int32(0))
        assert has_sparse_feature(s)

    def test_capacity_overflow_raises(self):
        import pytest
        with pytest.raises(ValueError, match="capacity"):
            SparseBag([1, 2, 3], [1, 1, 1], 2)


class TestVarLenBagE2E:
    def test_bag_flow_trains_sparse_linear(self, tmp_path):
        """TFRecord VarLen -> encoding='bag' -> SparseMiniBatch (ids,
        values) -> SparseLinear device-sparse training (the
        test_sparse_parse.py e2e flow without densification)."""
        vocab, classes, maxlen, batch, n = 24, 3, 6, 8, 96
        rs = np.random.RandomState(0)
        path = str(tmp_path / "bag.tfrecord")
        per_class = vocab // classes
        with TFRecordWriter(path) as w:
            for i in range(n):
                c = i % classes
                k = rs.randint(1, maxlen + 1)
                ids = rs.randint(c * per_class, (c + 1) * per_class,
                                 size=k).astype(np.int64)
                w.write(build_example_proto(
                    {"ids": ids, "y": np.asarray([c], np.int64)}))

        ds = ParsedExampleDataSet(
            [path], batch_size=batch, dense_keys=["y"], dense_shapes=[()],
            label_key="y", sparse_features=[
                VarLenFeature("ids", vocab, dtype="float32",
                              encoding="bag", max_nnz=maxlen)])
        b0 = next(iter(ds.data(train=False)))
        ids_arr, vals_arr = b0.input
        assert ids_arr.shape == (batch, maxlen)
        assert vals_arr.shape == (batch, maxlen)
        assert (ids_arr >= -1).all() and (ids_arr < vocab).all()

        model = nn.Sequential(nn.SparseLinear(vocab, classes),
                              nn.LogSoftMax())
        opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                              optim_method=SGD(learning_rate=0.5),
                              end_trigger=Trigger.max_epoch(12))
        opt.optimize()
        # the class is recoverable from the id range: training must
        # reach a confident fit
        logits, _ = model.apply(opt.params, opt.model_state,
                                Table(jnp.asarray(ids_arr),
                                      jnp.asarray(vals_arr)))
        pred = np.argmax(np.asarray(logits), axis=1)
        want = np.asarray(b0.target).ravel()[:batch]
        assert (pred == want).mean() >= 0.9
