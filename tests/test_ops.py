"""Forward-only op zoo tests (reference: nn/ops + nn/tf ControlOps).

Checks: numeric/structural op semantics vs numpy, stop_gradient behavior
(the 'backward forbidden' contract), control-flow modules under jit, and
feature-column host ops; plus serializer roundtrip for the ops namespace.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.core.table import Table
from bigdl_tpu.nn import ops
from bigdl_tpu.utils import serializer as ser


def t2(a, b):
    return Table(jnp.asarray(a), jnp.asarray(b))


class TestNumericOps:
    def test_comparisons(self):
        a, b = jnp.asarray([1.0, 2.0, 3.0]), jnp.asarray([2.0, 2.0, 2.0])
        cases = [(ops.Equal(), np.equal), (ops.NotEqual(), np.not_equal),
                 (ops.Greater(), np.greater), (ops.GreaterEqual(), np.greater_equal),
                 (ops.Less(), np.less), (ops.LessEqual(), np.less_equal)]
        for op, ref in cases:
            got, _ = op.apply({}, {}, t2(a, b))
            np.testing.assert_array_equal(np.asarray(got), ref(np.asarray(a), np.asarray(b)))

    def test_logical_and_reduce(self):
        x = jnp.asarray([[True, False], [True, True]])
        got, _ = ops.All(axis=1).apply({}, {}, x)
        np.testing.assert_array_equal(np.asarray(got), [False, True])
        got, _ = ops.Any(axis=0).apply({}, {}, x)
        np.testing.assert_array_equal(np.asarray(got), [True, True])
        got, _ = ops.LogicalNot().apply({}, {}, x)
        np.testing.assert_array_equal(np.asarray(got), ~np.asarray(x))

    def test_binary_math(self):
        a, b = jnp.asarray([7.0, -4.0]), jnp.asarray([3.0, 3.0])
        assert np.allclose(ops.Mod().apply({}, {}, t2(a, b))[0], [1.0, 2.0])
        assert np.allclose(ops.FloorDiv().apply({}, {}, t2(a, b))[0], [2.0, -2.0])
        assert np.allclose(ops.Maximum().apply({}, {}, t2(a, b))[0], [7.0, 3.0])
        assert np.allclose(ops.Minimum().apply({}, {}, t2(a, b))[0], [3.0, -4.0])
        assert np.allclose(ops.SquaredDifference().apply({}, {}, t2(a, b))[0],
                           [16.0, 49.0])


class TestStructuralOps:
    def test_gather_onehot(self):
        table = jnp.arange(12.0).reshape(4, 3)
        got, _ = ops.Gather().apply({}, {}, Table(table, jnp.asarray([2, 0])))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(table)[[2, 0]])
        oh, _ = ops.OneHot(4, on_value=5.0, off_value=-1.0).apply(
            {}, {}, jnp.asarray([1, 3]))
        assert oh.shape == (2, 4)
        assert float(oh[0, 1]) == 5.0 and float(oh[0, 0]) == -1.0

    def test_pad_slice_strided(self):
        x = jnp.arange(6.0).reshape(2, 3)
        padded, _ = ops.Pad([(1, 0), (0, 2)], value=9.0).apply({}, {}, x)
        assert padded.shape == (3, 5) and float(padded[0, 0]) == 9.0
        sliced, _ = ops.Slice([0, 1], [2, -1]).apply({}, {}, x)
        np.testing.assert_array_equal(np.asarray(sliced), np.asarray(x)[:, 1:])
        ss, _ = ops.StridedSlice([(None, None, 1), (2, None, -2)]).apply({}, {}, x)
        np.testing.assert_array_equal(np.asarray(ss), np.asarray(x)[:, 2::-2])

    def test_rank_shape_tile_argmax_cast(self):
        x = jnp.ones((2, 3))
        assert int(ops.Rank().apply({}, {}, x)[0]) == 2
        np.testing.assert_array_equal(np.asarray(ops.ShapeOp().apply({}, {}, x)[0]),
                                      [2, 3])
        tiled, _ = ops.Tile([2, 1]).apply({}, {}, x)
        assert tiled.shape == (4, 3)
        am, _ = ops.ArgMax(-1).apply({}, {}, jnp.asarray([[1.0, 9.0, 2.0]]))
        assert int(am[0]) == 1
        casted, _ = ops.Cast("int32").apply({}, {}, jnp.asarray([1.9]))
        assert casted.dtype == jnp.int32

    def test_topk_intopk_select(self):
        x = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
        tk, _ = ops.TopK(2).apply({}, {}, x)
        vals, idx = list(tk)
        np.testing.assert_array_equal(np.asarray(vals), [[5.0, 3.0]])
        np.testing.assert_array_equal(np.asarray(idx), [[1, 2]])
        hit, _ = ops.InTopK(2).apply({}, {}, Table(x, jnp.asarray([2])))
        assert bool(hit[0])
        miss, _ = ops.InTopK(2).apply({}, {}, Table(x, jnp.asarray([0])))
        assert not bool(miss[0])
        sel, _ = ops.SelectOp().apply(
            {}, {}, Table(jnp.asarray([True, False]), jnp.asarray([1.0, 1.0]),
                          jnp.asarray([2.0, 2.0])))
        np.testing.assert_array_equal(np.asarray(sel), [1.0, 2.0])

    def test_operation_stops_gradient(self):
        op = ops.Maximum()

        def f(a):
            y, _ = op.apply({}, {}, Table(a, jnp.zeros_like(a)))
            return jnp.sum(y * a)

        a = jnp.asarray([2.0, 3.0])
        g = jax.grad(f)(a)
        # gradient flows only through the second use of `a`, not the op output
        np.testing.assert_allclose(np.asarray(g), [2.0, 3.0])


class TestControlFlow:
    def test_cond(self, rng):
        then_m, else_m = nn.Linear(4, 4), nn.Linear(4, 4)
        cond = ops.Cond(then_m, else_m)
        params, state, _ = cond.build(rng, Table((), (2, 4)))
        x = jax.random.normal(jax.random.fold_in(rng, 1), (2, 4))

        @jax.jit
        def run(pred):
            y, _ = cond.apply(params, state, Table(pred, x))
            return y

        want_t, _ = then_m.apply(params["then"], state["then"], x)
        want_e, _ = else_m.apply(params["else"], state["else"], x)
        # atol floors the near-zero entries: the jitted branch may fuse the
        # matmul+bias differently from the eager reference forward
        np.testing.assert_allclose(np.asarray(run(jnp.asarray(True))),
                                   np.asarray(want_t), rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(run(jnp.asarray(False))),
                                   np.asarray(want_e), rtol=1e-6, atol=1e-6)

    def test_while_loop(self):
        double = nn.MulConstant(2.0)
        loop = ops.WhileLoop(double, cond_fn=lambda v: jnp.max(v) < 100.0,
                             max_iterations=50)
        params, state, _ = loop.build(jax.random.PRNGKey(0), (2,))
        y = jax.jit(lambda x: loop.apply(params, state, x)[0])(
            jnp.asarray([1.0, 1.0]))
        assert float(y[0]) == 128.0  # 1 -> 2 -> ... -> 128 (first >= 100)


class TestFeatureColumns:
    def test_hash_bucket_deterministic(self):
        op = ops.CategoricalColHashBucket(100)
        a, _ = op.apply({}, {}, np.asarray(["cat", "dog", "cat"], dtype=object))
        assert int(a[0]) == int(a[2])
        assert 0 <= int(a[1]) < 100
        b, _ = op.apply({}, {}, np.asarray(["cat"], dtype=object))
        assert int(b[0]) == int(a[0])  # stable across calls/processes

    def test_cross_col(self):
        op = ops.CrossCol(1000)
        out, _ = op.apply({}, {}, [np.asarray(["a", "b"], dtype=object),
                                   np.asarray(["x", "y"], dtype=object)])
        out2, _ = op.apply({}, {}, [np.asarray(["a"], dtype=object),
                                    np.asarray(["x"], dtype=object)])
        assert int(out[0]) == int(out2[0])
        assert int(out[0]) != int(out[1])

    def test_indicator_col(self):
        out, _ = ops.IndicatorCol(5).apply({}, {}, jnp.asarray([[1, 3], [0, 0]]))
        np.testing.assert_array_equal(np.asarray(out),
                                      [[0, 1, 0, 1, 0], [1, 0, 0, 0, 0]])

    def test_kv2tensor_mkstring(self):
        kv, _ = ops.Kv2Tensor(feature_num=4).apply(
            {}, {}, np.asarray(["0:1.5,2:3", "1:2"], dtype=object))
        np.testing.assert_allclose(np.asarray(kv),
                                   [[1.5, 0, 3.0, 0], [0, 2.0, 0, 0]])
        s, _ = ops.MkString().apply({}, {}, np.asarray([[1.0, 2.5], [3.0, 4.0]]))
        assert list(s) == ["1,2.5", "3,4"]


def test_ops_serialize_roundtrip():
    for op in (ops.OneHot(4, 2.0, -1.0), ops.Pad([(1, 1)], 3.0),
               ops.Slice([0], [2]), ops.TopK(3), ops.Cast("int32"),
               ops.CategoricalColHashBucket(64),
               ops.Kv2Tensor(feature_num=8)):
        spec = ser.module_to_spec(op)
        assert spec["class"].startswith("ops.")
        rebuilt = ser.module_from_spec(spec)
        assert type(rebuilt) is type(op)
        assert ser.module_to_spec(rebuilt) == spec
