"""bigdl_tpu.analysis — the TPU-hostile-pattern linter + strict transfer guard.

Every rule family gets at least one positive fixture (the pattern is
caught) and one negative fixture (the idiomatic rewrite passes) so the
linter's precision/recall contract is pinned, not assumed.  The runtime
half pins the empirical `jax.transfer_guard("disallow")` semantics the
docs claim: implicit h2d raises, d2h pulls do NOT (which is exactly why
the static linter owns the d2h side).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.analysis import (
    HOT_PATH_RULES,
    RULES,
    analyze_sources,
    strict_transfers,
    strict_transfers_enabled,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_CLI = os.path.join(REPO, "tools", "tpu_lint.py")


def _findings(src, hot_roots=None, path="mod.py"):
    return analyze_sources({path: src}, hot_roots=hot_roots)


def _rules(src, hot_roots=None):
    return {f.rule for f in _findings(src, hot_roots=hot_roots)}


# ----------------------------------------------------------------------
# rule family: host-sync
# ----------------------------------------------------------------------

class TestHostSync:
    def test_positive_float_pull_in_hot_loop(self):
        src = """
import jax.numpy as jnp

def train_loop(xs):
    total = jnp.zeros(())
    for x in xs:
        total = total + x
        print(float(total))
    return total
"""
        assert "host-sync" in _rules(src, hot_roots=[r"train_loop$"])

    def test_positive_np_asarray_of_device_value(self):
        src = """
import numpy as np
import jax.numpy as jnp

def train_loop(xs):
    total = jnp.zeros(())
    for x in xs:
        total = total + x
        log = np.asarray(total)
    return log
"""
        assert "host-sync" in _rules(src, hot_roots=[r"train_loop$"])

    def test_positive_branch_on_traced_value(self):
        src = """
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    y = jnp.sum(x)
    if y > 0:
        return y
    return -y
"""
        assert "host-sync" in _rules(src)

    def test_negative_device_get_is_sanctioned(self):
        src = """
import jax
import jax.numpy as jnp

def train_loop(xs):
    total = jnp.zeros(())
    for x in xs:
        total = total + x
    return jax.device_get(total)
"""
        assert "host-sync" not in _rules(src, hot_roots=[r"train_loop$"])

    def test_negative_cold_function_not_flagged(self):
        src = """
import jax.numpy as jnp

def summarize(xs):
    total = jnp.zeros(())
    for x in xs:
        total = total + x
        print(float(total))
    return total
"""
        assert _rules(src) == set()  # no hot root matches `summarize`


# ----------------------------------------------------------------------
# rule family: recompile
# ----------------------------------------------------------------------

class TestRecompile:
    def test_positive_self_read_inside_jit(self):
        src = """
import jax

class Trainer:
    def build(self):
        def step(x):
            def inner(y):
                return y * 2
            return inner(x) * self.scale
        return jax.jit(step)
"""
        assert "recompile" in _rules(src)

    def test_positive_host_scalar_into_jitted_call_in_hot_loop(self):
        src = """
import jax

@jax.jit
def step(x):
    return x * 2

def train_loop(xs):
    acc = []
    for x in xs:
        scale = len(acc) + 1
        acc.append(step(scale))
    return acc
"""
        assert "recompile" in _rules(src, hot_roots=[r"train_loop$"])

    def test_negative_hoisted_self_and_device_args(self):
        src = """
import jax

class Trainer:
    def build(self):
        scale = self.scale
        def step(x):
            return x * scale
        return jax.jit(step)

@jax.jit
def double(x):
    return x * 2

def train_loop(xs):
    out = []
    for x in xs:
        out.append(double(x))
    return out
"""
        assert "recompile" not in _rules(src, hot_roots=[r"train_loop$"])


# ----------------------------------------------------------------------
# rule family: tracer-leak
# ----------------------------------------------------------------------

class TestTracerLeak:
    def test_positive_store_on_self_inside_jit(self):
        src = """
import jax

class Model:
    def build(self):
        @jax.jit
        def step(x):
            y = x * 2
            self.cache = y
            return y
        return step
"""
        assert "tracer-leak" in _rules(src)

    def test_positive_store_into_captured_container(self):
        src = """
import jax

def build(cache):
    @jax.jit
    def step(x):
        y = x * 2
        cache["y"] = y
        return y
    return step
"""
        assert "tracer-leak" in _rules(src)

    def test_negative_local_container_is_fine(self):
        src = """
import jax

@jax.jit
def step(x):
    scratch = {}
    scratch["y"] = x * 2
    return scratch["y"]
"""
        assert "tracer-leak" not in _rules(src)


# ----------------------------------------------------------------------
# rule family: concurrency
# ----------------------------------------------------------------------

class TestConcurrency:
    def test_positive_thread_without_daemon_or_join(self):
        src = """
import threading

class Pump:
    def __init__(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass
"""
        assert "concurrency" in _rules(src)

    def test_positive_unbounded_queue_get_in_worker_class(self):
        src = """
import queue
import threading

class Pump:
    def __init__(self):
        self._q = queue.Queue(maxsize=2)
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return

    def close(self):
        self._t.join(timeout=5.0)
"""
        assert "concurrency" in _rules(src)

    def test_positive_shared_list_mutated_without_lock(self):
        src = """
import threading

class Tracker:
    def __init__(self):
        self.items = []
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        self.items.append(1)

    def close(self):
        self.items.append(2)
        self._t.join(timeout=1.0)
"""
        assert "concurrency" in _rules(src)

    def test_negative_full_discipline(self):
        src = """
import queue
import threading

class Pump:
    def __init__(self):
        self._q = queue.Queue(maxsize=2)
        self._lock = threading.Lock()
        self.items = []
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            if item is None:
                return
            with self._lock:
                self.items.append(item)

    def close(self):
        self._q.put(None, timeout=1.0)
        self._t.join(timeout=5.0)
        with self._lock:
            return list(self.items)
"""
        assert "concurrency" not in _rules(src)

    # -- multiprocessing idioms (reader-pool family) -------------------

    def test_positive_process_without_daemon_or_join(self):
        src = """
import multiprocessing as mp

class Pool:
    def __init__(self):
        self._p = mp.Process(target=self._run)
        self._p.start()

    def _run(self):
        pass
"""
        assert "concurrency" in _rules(src)

    def test_positive_unbounded_mp_queue_get(self):
        src = """
import multiprocessing as mp

class Pool:
    def __init__(self):
        self._ctx = mp.get_context("fork")
        self._q = self._ctx.Queue(maxsize=4)
        self._p = self._ctx.Process(target=self._run, daemon=True)
        self._p.start()

    def _run(self):
        pass

    def __next__(self):
        return self._q.get()

    def close(self):
        self._p.join(timeout=1.0)
"""
        assert "concurrency" in _rules(src)

    def test_positive_unbounded_process_join_on_shutdown(self):
        src = """
import multiprocessing as mp

class Pool:
    def __init__(self):
        self._p = mp.Process(target=self._run, daemon=True)
        self._p.start()

    def _run(self):
        pass

    def close(self):
        self._p.join()
"""
        assert "concurrency" in _rules(src)

    def test_negative_mp_full_discipline(self):
        src = """
import queue
import multiprocessing as mp

class Pool:
    def __init__(self):
        self._ctx = mp.get_context("fork")
        self._q = self._ctx.Queue(maxsize=4)
        self._p = self._ctx.Process(target=self._run, daemon=True)
        self._p.start()

    def _run(self):
        pass

    def __next__(self):
        try:
            return self._q.get(timeout=0.05)
        except queue.Empty:
            return None

    def close(self):
        self._p.join(timeout=1.0)
        if self._p.is_alive():
            self._p.terminate()
"""
        assert "concurrency" not in _rules(src)

    def test_negative_unbounded_thread_join_outside_process_scope(self):
        # the unbounded-join shutdown rule is scoped to process-owning
        # classes: a thread-owning class keeps the (join-with-timeout)
        # guidance but plain join() alone is not flagged there
        src = """
import threading

class Pump:
    def __init__(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        pass

    def close(self):
        self._t.join()
"""
        assert "concurrency" not in _rules(src)


# ----------------------------------------------------------------------
# rule family: donation
# ----------------------------------------------------------------------

class TestDonation:
    def test_positive_read_after_donating_call(self):
        src = """
import jax

def _step(p, x):
    return p + x

step = jax.jit(_step, donate_argnums=(0,))

def run(p, xs):
    out = None
    for x in xs:
        out = step(p, x)
        norm = p.sum()
    return out, norm
"""
        assert "donation" in _rules(src)

    def test_negative_rebinding_loop(self):
        src = """
import jax

def _step(p, x):
    return p + x

step = jax.jit(_step, donate_argnums=(0,))

def run(p, xs):
    for x in xs:
        p = step(p, x)
    return p
"""
        assert "donation" not in _rules(src)


# ----------------------------------------------------------------------
# rule family: blocking-io
# ----------------------------------------------------------------------

class TestBlockingIO:
    def test_positive_open_inside_jit(self):
        src = """
import jax

@jax.jit
def step(x):
    with open('/tmp/debug.log', 'w') as fh:
        fh.write('hi')
    return x * 2
"""
        assert "blocking-io" in _rules(src)

    def test_positive_sleep_in_hot_loop(self):
        src = """
import time

def train_loop(xs):
    out = []
    for x in xs:
        time.sleep(0.01)
        out.append(x)
    return out
"""
        assert "blocking-io" in _rules(src, hot_roots=[r"train_loop$"])

    def test_negative_logging_and_cold_io(self):
        src = """
import logging
import time

logger = logging.getLogger(__name__)

def train_loop(xs):
    out = []
    for x in xs:
        logger.info("step %d", len(out))
        out.append(x)
    return out

def report(path, text):
    with open(path, 'w') as fh:
        fh.write(text)
"""
        assert "blocking-io" not in _rules(src, hot_roots=[r"train_loop$"])


# ----------------------------------------------------------------------
# suppressions + fingerprints
# ----------------------------------------------------------------------

class TestSuppressionsAndFingerprints:
    SRC = """
import jax.numpy as jnp

def train_loop(xs):
    total = jnp.zeros(())
    for x in xs:
        total = total + x
        log = float(total){SUPPRESS}
    return log
"""

    def test_inline_disable_silences_one_rule(self):
        noisy = self.SRC.replace("{SUPPRESS}", "")
        quiet = self.SRC.replace("{SUPPRESS}",
                                 "  # tpu-lint: disable=host-sync")
        assert "host-sync" in _rules(noisy, hot_roots=[r"train_loop$"])
        assert "host-sync" not in _rules(quiet, hot_roots=[r"train_loop$"])

    def test_def_line_disable_all_covers_function(self):
        src = self.SRC.replace("{SUPPRESS}", "").replace(
            "def train_loop(xs):",
            "def train_loop(xs):  # tpu-lint: disable=all")
        assert _rules(src, hot_roots=[r"train_loop$"]) == set()

    def test_fingerprint_survives_line_moves(self):
        noisy = self.SRC.replace("{SUPPRESS}", "")
        shifted = "\n\n\n" + noisy  # same code, three lines lower
        fp = {f.fingerprint()
              for f in _findings(noisy, hot_roots=[r"train_loop$"])}
        fp2 = {f.fingerprint()
               for f in _findings(shifted, hot_roots=[r"train_loop$"])}
        assert fp and fp == fp2


# ----------------------------------------------------------------------
# CLI + baseline policy
# ----------------------------------------------------------------------

HOT_FIXTURE = """
import jax.numpy as jnp

class Optimizer:
    def _optimize_impl(self, xs):
        total = jnp.zeros(())
        for x in xs:
            total = total + x
            log = float(total)
        return log
"""

COLD_FIXTURE = """
import threading

class Pump:
    def __init__(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass
"""


def _run_cli(*args):
    return subprocess.run([sys.executable, LINT_CLI, *args],
                          capture_output=True, text=True, cwd=REPO)


class TestCli:
    def test_findings_exit_1_then_baseline_exits_0(self, tmp_path):
        (tmp_path / "pump.py").write_text(COLD_FIXTURE)
        baseline = tmp_path / "baseline.json"
        r = _run_cli(str(tmp_path))
        assert r.returncode == 1 and "concurrency" in r.stdout
        r = _run_cli(str(tmp_path), "--baseline", str(baseline),
                     "--write-baseline")
        assert r.returncode == 0, r.stderr
        r = _run_cli(str(tmp_path), "--baseline", str(baseline))
        assert r.returncode == 0 and "clean" in r.stdout

    def test_hot_path_rules_cannot_be_baselined(self, tmp_path):
        (tmp_path / "opt.py").write_text(HOT_FIXTURE)
        baseline = tmp_path / "baseline.json"
        r = _run_cli(str(tmp_path), "--baseline", str(baseline),
                     "--write-baseline")
        assert r.returncode == 2
        assert "refusing" in r.stderr
        assert not baseline.exists()

    def test_handcrafted_hot_baseline_is_rejected(self, tmp_path):
        (tmp_path / "opt.py").write_text(HOT_FIXTURE)
        baseline = tmp_path / "baseline.json"
        baseline.write_text(json.dumps({
            "version": 1,
            "suppressions": [{"fingerprint": "deadbeefdeadbeef",
                              "rule": "host-sync", "path": "opt.py",
                              "func": "Optimizer._optimize_impl",
                              "message": "sneaky"}]}))
        r = _run_cli(str(tmp_path), "--baseline", str(baseline))
        assert r.returncode == 2
        assert "grandfathered" in r.stderr

    def test_unknown_rule_is_config_error(self, tmp_path):
        (tmp_path / "empty.py").write_text("x = 1\n")
        r = _run_cli(str(tmp_path), "--rules", "no-such-rule")
        assert r.returncode == 2

    def test_rules_registry_consistent(self):
        assert HOT_PATH_RULES < set(RULES)

    def test_repo_tree_is_clean(self):
        r = _run_cli("bigdl_tpu/", "examples/", "benchmarks/", "--baseline",
                     os.path.join(REPO, "tools", "tpu_lint_baseline.json"))
        assert r.returncode == 0, r.stdout + r.stderr


# ----------------------------------------------------------------------
# runtime strict-transfer guard
# ----------------------------------------------------------------------

class TestStrictTransfers:
    def test_env_flag_parsing(self, monkeypatch):
        monkeypatch.delenv("BIGDL_TPU_STRICT_TRANSFERS", raising=False)
        assert not strict_transfers_enabled()
        monkeypatch.setenv("BIGDL_TPU_STRICT_TRANSFERS", "1")
        assert strict_transfers_enabled()
        monkeypatch.setenv("BIGDL_TPU_STRICT_TRANSFERS", "0")
        assert not strict_transfers_enabled()
        # explicit override beats the env both ways
        monkeypatch.setenv("BIGDL_TPU_STRICT_TRANSFERS", "1")
        assert not strict_transfers_enabled(False)
        monkeypatch.delenv("BIGDL_TPU_STRICT_TRANSFERS")
        assert strict_transfers_enabled(True)

    def test_implicit_h2d_raises_under_guard(self):
        f = jax.jit(lambda x: x + 1)
        f(jnp.float32(1.0))  # compile OUTSIDE the guard
        with strict_transfers(True):
            with pytest.raises(Exception, match="(?i)transfer"):
                f(2.0)  # python scalar -> implicit h2d put

    def test_device_args_pass_under_guard(self):
        f = jax.jit(lambda x: x + 1)
        x = jax.device_put(jnp.float32(1.0))
        with strict_transfers(True):
            assert float(jax.device_get(f(x))) == 2.0

    def test_d2h_pull_is_not_caught(self):
        # the asymmetry the docs warn about: transfer_guard("disallow")
        # does NOT catch device->host pulls — that's the linter's job.
        # If a jax upgrade ever flips this, the docs need rewording.
        y = jnp.float32(3.0) * 2
        with strict_transfers(True):
            assert float(y) == 6.0

    def test_disabled_guard_is_a_noop(self):
        f = jax.jit(lambda x: x + 1)
        f(jnp.float32(1.0))
        with strict_transfers(False):
            assert float(jax.device_get(f(2.0))) == 3.0

    def test_conftest_fixture(self, strict_transfers):
        f = jax.jit(lambda x: x * 3)
        # np.float32, not jnp.float32: the latter lowers through
        # convert_element_type — itself an implicit h2d the guard rejects
        x = jax.device_put(np.float32(2.0))
        assert float(jax.device_get(f(x))) == 6.0


class TestStrictTrainerIntegration:
    def _fit(self, monkeypatch, inject):
        import bigdl_tpu.nn as nn
        import bigdl_tpu.optim.optimizer as opt_mod
        from bigdl_tpu.dataset import ArrayDataSet, MiniBatch
        from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger

        if inject:
            # reintroduce the exact bug the linter caught at
            # optimizer.py:_optimize_impl (pre-fix): the per-step fold_in
            # index passed as a raw Python int — an implicit h2d put
            # inside the guarded hot section
            real = jax.jit(jax.random.fold_in)
            monkeypatch.setattr(opt_mod, "_fold_in",
                                lambda key, i: real(key, int(i)))

        rs = np.random.RandomState(0)
        items = [MiniBatch(jnp.asarray(rs.rand(8, 4), jnp.float32),
                           jnp.asarray(rs.randint(0, 2, 8)))
                 for _ in range(4)]
        model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2),
                              nn.LogSoftMax())
        opt = LocalOptimizer(model, ArrayDataSet(items),
                             nn.ClassNLLCriterion(),
                             optim_method=SGD(learning_rate=0.1),
                             end_trigger=Trigger.max_epoch(1))
        opt.set_strict_transfers(True)
        return opt.optimize()

    def test_injected_host_sync_raises(self, monkeypatch):
        monkeypatch.setenv("BIGDL_TPU_STRICT_TRANSFERS", "1")
        with pytest.raises(Exception, match="(?i)transfer"):
            self._fit(monkeypatch, inject=True)

    def test_clean_hot_loop_fits_under_guard(self, monkeypatch):
        # regression: the shipped hot loop must stay strict-clean
        self._fit(monkeypatch, inject=False)


# ----------------------------------------------------------------------
# rule family: lock-order (static lock-discipline pass)
# ----------------------------------------------------------------------

class TestLockOrder:
    def test_positive_abba_cycle_between_typed_classes(self):
        src = """
import threading


class Pool:
    def __init__(self, store: "Store"):
        self.store = store
        self._lock = threading.Lock()

    def claim(self):
        with self._lock:
            self.store.evict()

    def free(self):
        with self._lock:
            pass


class Store:
    def __init__(self, pool: "Pool"):
        self.pool = pool
        self._lock = threading.Lock()

    def evict(self):
        with self._lock:
            pass

    def publish(self):
        with self._lock:
            self.pool.free()
"""
        fs = [f for f in _findings(src) if f.rule == "lock-order"]
        assert fs, "ABBA cycle through typed attrs must be reported"
        assert any("Pool._lock" in f.message and "Store._lock" in f.message
                   for f in fs)

    def test_negative_single_global_order(self):
        src = """
import threading


class Pool:
    def __init__(self, store: "Store"):
        self.store = store
        self._lock = threading.Lock()

    def free(self):
        with self._lock:
            pass


class Store:
    def __init__(self, pool: "Pool"):
        self.pool = pool
        self._lock = threading.Lock()

    def evict(self):
        with self._lock:
            self.pool.free()

    def publish(self):
        with self._lock:
            self.pool.free()
"""
        assert "lock-order" not in _rules(src)

    def test_positive_self_deadlock_on_nonreentrant_self_call(self):
        src = """
import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()

    def step(self):
        with self._lock:
            self._flush()

    def _flush(self):
        with self._lock:
            pass
"""
        fs = [f for f in _findings(src) if f.rule == "lock-order"]
        assert any("self-deadlock" in f.message for f in fs)

    def test_negative_rlock_self_call_is_reentrant(self):
        src = """
import threading


class Engine:
    def __init__(self):
        self._lock = threading.RLock()

    def step(self):
        with self._lock:
            self._flush()

    def _flush(self):
        with self._lock:
            pass
"""
        assert "lock-order" not in _rules(src)

    def test_inline_disable_silences(self):
        src = """
import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()

    def step(self):
        with self._lock:
            self._flush()  # tpu-lint: disable=lock-order

    def _flush(self):
        with self._lock:
            pass
"""
        assert "lock-order" not in _rules(src)


# ----------------------------------------------------------------------
# rule family: unguarded-state
# ----------------------------------------------------------------------

UNGUARDED_SRC = """
import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while True:
            with self._lock:
                self.items.append(1)

    def push(self, x):
        with self._lock:
            self.items.append(x)

    def flush(self):
        self.items.clear()%s
"""


class TestUnguardedState:
    def test_positive_majority_guarded_minority_not(self):
        fs = [f for f in _findings(UNGUARDED_SRC % "")
              if f.rule == "unguarded-state"]
        assert fs, "2 guarded + 1 unguarded cross-thread site must report"
        assert any("items" in f.message for f in fs)

    def test_negative_all_sites_guarded(self):
        src = (UNGUARDED_SRC % "").replace(
            "        self.items.clear()",
            "        with self._lock:\n"
            "            self.items.clear()")
        assert "unguarded-state" not in _rules(src)

    def test_negative_no_thread_ownership_no_rule(self):
        src = (UNGUARDED_SRC % "").replace(
            "        self._t = threading.Thread("
            "target=self._loop, daemon=True)\n"
            "        self._t.start()\n", "")
        assert "unguarded-state" not in _rules(src)

    def test_inline_disable_silences(self):
        src = UNGUARDED_SRC % "  # tpu-lint: disable=unguarded-state"
        assert "unguarded-state" not in _rules(src)


# ----------------------------------------------------------------------
# rule family: blocking-under-lock
# ----------------------------------------------------------------------

class TestBlockingUnderLock:
    def test_positive_sleep_under_lock_on_hot_root(self):
        src = """
import threading
import time


class DeviceFeed:
    def __init__(self):
        self._lock = threading.Lock()

    def _worker(self):
        with self._lock:
            time.sleep(1.0)
"""
        fs = [f for f in _findings(src, hot_roots=[r"_worker$"])
              if f.rule == "blocking-under-lock"]
        assert fs and any("time.sleep" in f.message for f in fs)

    def test_positive_caller_held_lock_reaches_helper(self):
        src = """
import threading
import time


class MicroBatcher:
    def __init__(self):
        self._lock = threading.Lock()

    def _loop(self):
        with self._lock:
            self._drain()

    def _drain(self):
        time.sleep(0.5)
"""
        fs = [f for f in _findings(src, hot_roots=[r"_loop$"])
              if f.rule == "blocking-under-lock"]
        assert fs, "lock held by the caller must count (caller-held " \
                   "inference)"

    def test_negative_blocking_outside_lock(self):
        src = """
import threading
import time


class DeviceFeed:
    def __init__(self):
        self._lock = threading.Lock()

    def _worker(self):
        with self._lock:
            n = 1
        time.sleep(1.0)
"""
        assert "blocking-under-lock" not in _rules(
            src, hot_roots=[r"_worker$"])

    def test_negative_bounded_queue_get_under_lock(self):
        src = """
import queue
import threading


class DeviceFeed:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()

    def _worker(self):
        with self._lock:
            item = self._q.get(timeout=0.5)
        return item
"""
        assert "blocking-under-lock" not in _rules(
            src, hot_roots=[r"_worker$"])

    def test_hot_path_rule_never_baselinable(self):
        assert "lock-order" in HOT_PATH_RULES
        assert "blocking-under-lock" in HOT_PATH_RULES
        assert "unguarded-state" not in HOT_PATH_RULES


# ----------------------------------------------------------------------
# the static lock graph surface
# ----------------------------------------------------------------------

class TestLockGraph:
    def test_graph_nodes_edges_and_dot(self):
        from bigdl_tpu.analysis.linter import project_for_sources
        src = """
import threading


class Store:
    def __init__(self, pool: "Pool"):
        self.pool = pool
        self._lock = threading.Lock()

    def evict(self):
        with self._lock:
            self.pool.free()


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._done = threading.Event()

    def free(self):
        with self._lock:
            self._done.set()
"""
        proj = project_for_sources({"mod.py": src})
        g = proj.lock_graph
        assert {"Store._lock", "Pool._lock", "Pool._done"} <= set(g.nodes)
        assert ("Store._lock", "Pool._lock") in g.edges
        assert g.edges[("Store._lock", "Pool._lock")].strong
        # Event internal-lock edge: free() holds Pool._lock across set()
        assert ("Pool._lock", "Pool._done") in g.edges
        dot = g.to_dot()
        assert "digraph" in dot and "Store._lock" in dot

    def test_condition_aliases_its_backing_lock(self):
        from bigdl_tpu.analysis.linter import project_for_sources
        src = """
import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)

    def kick(self):
        with self._cond:
            pass

    def wait_done(self):
        with self._lock:
            pass
"""
        proj = project_for_sources({"mod.py": src})
        g = proj.lock_graph
        assert "Engine._lock" in g.nodes
        assert "Engine._cond" not in g.nodes  # alias, not a second lock
