"""Unfrozen TF graphs: VariableV2 / VarHandleOp import with checkpoint
restore — the reference's real-world TF story (TensorflowLoader.scala:456
filters Variable endpoints and binds checkpoint values;
scripts/export_tf_checkpoint.py + nn/tf/StateOps.scala support the flow).

Fixtures are generated with the env's real TF (graph-mode sessions inside
an explicit tf.Graph — no global eager disable needed); the framework's
own bundle decode (utils/tf_checkpoint.py) never touches the TF runtime.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

tf = pytest.importorskip("tensorflow")

import bigdl_tpu.nn as nn  # noqa: E402
from bigdl_tpu.utils.tensorflow import load_tensorflow  # noqa: E402
from bigdl_tpu.utils.tf_checkpoint import read_checkpoint  # noqa: E402


# heavyweight tier: differential oracles / trainers / registry sweeps;
# the quick tier is 'pytest -m "not slow"' (README Testing)
pytestmark = pytest.mark.slow

N, H, W, C = 4, 8, 8, 3
FILTERS, CLASSES = 6, 5


def _build_v1_conv_graph(tmp_path, use_resource=False):
    """conv(var) -> bias(var) -> relu -> flatten -> matmul(var) -> out,
    saved UNFROZEN with a v2-format checkpoint."""
    rs = np.random.RandomState(7)
    g = tf.Graph()
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, [N, H, W, C], name="x")
        k = tf.compat.v1.Variable(
            rs.randn(3, 3, C, FILTERS).astype(np.float32) * 0.2,
            name="conv_w", use_resource=use_resource)
        cb = tf.compat.v1.Variable(rs.randn(FILTERS).astype(np.float32) * 0.1,
                                   name="conv_b", use_resource=use_resource)
        w = tf.compat.v1.Variable(
            rs.randn(H * W * FILTERS, CLASSES).astype(np.float32) * 0.05,
            name="fc_w", use_resource=use_resource)
        y = tf.nn.conv2d(x, k, strides=[1, 1, 1, 1], padding="SAME")
        y = tf.nn.relu(tf.nn.bias_add(y, cb))
        y = tf.reshape(y, [N, -1])
        y = tf.linalg.matmul(y, w)
        y = tf.identity(y, name="out")
        init = tf.compat.v1.global_variables_initializer()
        saver = tf.compat.v1.train.Saver()
    xv = rs.randn(N, H, W, C).astype(np.float32)
    with tf.compat.v1.Session(graph=g) as sess:
        sess.run(init)
        ref = sess.run(y, {x: xv})
        prefix = saver.save(sess, str(tmp_path / "model.ckpt"))
    pb = str(tmp_path / "graph.pb")
    with open(pb, "wb") as fh:
        fh.write(g.as_graph_def().SerializeToString())
    return pb, prefix, xv, ref


class TestBundleReader:
    def test_matches_tf_loader(self, tmp_path):
        pb, prefix, _, _ = _build_v1_conv_graph(tmp_path)
        ours = read_checkpoint(prefix)
        reader = tf.train.load_checkpoint(prefix)
        keys = [k for k in reader.get_variable_to_shape_map()]
        assert set(keys) <= set(ours) | {"_CHECKPOINTABLE_OBJECT_GRAPH"}
        for k in keys:
            if k in ours:
                np.testing.assert_array_equal(ours[k], reader.get_tensor(k))
        assert {"conv_w", "conv_b", "fc_w"} <= set(ours)

    def test_prefix_not_file_error(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="PREFIX"):
            read_checkpoint(str(tmp_path / "nothing"))


class TestVariableImport:
    def test_checkpoint_forward_matches_tf(self, tmp_path):
        pb, prefix, xv, ref = _build_v1_conv_graph(tmp_path)
        g, gp, gs = load_tensorflow(pb, ["x"], ["out"], [(N, H, W, C)],
                                    checkpoint=prefix)
        y = np.asarray(g.apply(gp, gs, jnp.asarray(xv))[0])
        np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)

    def test_initializer_fold_without_checkpoint(self, tmp_path):
        """No checkpoint: variables bind their const-foldable initializer
        Assign — matching TF right after global_variables_initializer."""
        pb, _, xv, ref = _build_v1_conv_graph(tmp_path)
        g, gp, gs = load_tensorflow(pb, ["x"], ["out"], [(N, H, W, C)])
        y = np.asarray(g.apply(gp, gs, jnp.asarray(xv))[0])
        np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)

    def test_resource_variables(self, tmp_path):
        """VarHandleOp/ReadVariableOp (TF2-style resource variables)."""
        pb, prefix, xv, ref = _build_v1_conv_graph(tmp_path,
                                                   use_resource=True)
        g, gp, gs = load_tensorflow(pb, ["x"], ["out"], [(N, H, W, C)],
                                    checkpoint=prefix)
        y = np.asarray(g.apply(gp, gs, jnp.asarray(xv))[0])
        np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)

    def test_variables_are_trainable_params(self, tmp_path):
        pb, prefix, _, _ = _build_v1_conv_graph(tmp_path)
        g, gp, gs = load_tensorflow(pb, ["x"], ["out"], [(N, H, W, C)],
                                    checkpoint=prefix)
        names = " ".join(
            jax.tree_util.keystr(path)
            for path, _ in jax.tree_util.tree_flatten_with_path(gp)[0])
        assert "conv_w" in names and "fc_w" in names, names

    def test_reversed_node_order_imports(self, tmp_path):
        """GraphDef order is not topological (grappler/transform_graph
        rewrites reorder nodes): consumers listed BEFORE the variables
        they read must defer and retry, not crash or misfold."""
        pb, prefix, xv, ref = _build_v1_conv_graph(tmp_path)
        import bigdl_tpu.proto  # noqa: F401
        import tf_graph_pb2 as tfp2

        gd = tfp2.GraphDef()
        with open(pb, "rb") as fh:
            gd.ParseFromString(fh.read())
        rev = list(gd.node)[::-1]
        del gd.node[:]
        for n in rev:
            gd.node.add().CopyFrom(n)
        pb2 = str(tmp_path / "reversed.pb")
        with open(pb2, "wb") as fh:
            fh.write(gd.SerializeToString())
        g, gp, gs = load_tensorflow(pb2, ["x"], ["out"], [(N, H, W, C)],
                                    checkpoint=prefix)
        y = np.asarray(g.apply(gp, gs, jnp.asarray(xv))[0])
        np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-5)

    def test_checkpoint_missing_variable_is_loud(self, tmp_path):
        """An explicit checkpoint that lacks a graph variable must fail,
        never silently fall back to the untrained initializer."""
        pb, prefix, _, _ = _build_v1_conv_graph(tmp_path)
        from bigdl_tpu.utils import tensorflow as tf_mod

        ck = read_checkpoint(prefix)
        ck.pop("conv_w")
        real = tf_mod.load_tensorflow

        g = tf.Graph  # keep flake quiet; not used
        import bigdl_tpu.utils.tf_checkpoint as ckpt_mod
        orig = ckpt_mod.read_checkpoint
        ckpt_mod.read_checkpoint = lambda p: ck
        try:
            with pytest.raises(ValueError, match="not found in the checkpoint"):
                real(pb, ["x"], ["out"], [(N, H, W, C)], checkpoint=prefix)
        finally:
            ckpt_mod.read_checkpoint = orig

    def test_missing_value_is_loud(self, tmp_path):
        """A variable with neither checkpoint nor foldable initializer
        must fail loudly, not import garbage."""
        g = tf.Graph()
        with g.as_default():
            x = tf.compat.v1.placeholder(tf.float32, [2, 3], name="x")
            w = tf.compat.v1.Variable(
                tf.random.normal([3, 2]),  # non-const initializer
                name="w", use_resource=False)
            tf.linalg.matmul(x, w, name="out")
        pb = str(tmp_path / "graph.pb")
        with open(pb, "wb") as fh:
            fh.write(g.as_graph_def().SerializeToString())
        with pytest.raises(ValueError, match="checkpoint"):
            load_tensorflow(pb, ["x"], ["out"], [(2, 3)])


class TestFineTune:
    def test_session_finetunes_checkpointed_graph(self, tmp_path):
        """Fine-tune the restored (unfrozen) graph via Session.train:
        loss decreases and the conv/fc variables move off their
        checkpoint values."""
        from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch
        from bigdl_tpu.optim import SGD, Trigger
        from bigdl_tpu.utils.session import Session

        pb, prefix, xv, _ = _build_v1_conv_graph(tmp_path)
        rs = np.random.RandomState(3)
        labels = (np.arange(N) % CLASSES).astype(np.int32)
        samples = [Sample.from_ndarray(xv[i], labels[i]) for i in range(N)]
        ds = ArrayDataSet(samples).transform(SampleToMiniBatch(N))

        sess = Session(pb, ["x"], [(N, H, W, C)], checkpoint=prefix)
        crit = nn.CrossEntropyCriterion()
        before = read_checkpoint(prefix)

        def loss_of():
            out, _ = sess.model.apply(sess.params, sess.state,
                                      jnp.asarray(xv))
            return float(crit.forward(out, jnp.asarray(labels)))

        sess.train(["out"], ds, crit,
                   optim_method=SGD(learning_rate=0.5),
                   end_when=Trigger.max_epoch(30))
        after_loss = loss_of()
        # params moved off the checkpoint and the fit improved
        moved = np.abs(np.asarray(sess.params["conv_w"]["value"])
                       - before["conv_w"]).max()
        assert moved > 1e-4, moved
        g0, gp0, gs0 = load_tensorflow(pb, ["x"], ["out"], [(N, H, W, C)],
                                       checkpoint=prefix)
        out0, _ = g0.apply(gp0, gs0, jnp.asarray(xv))
        loss0 = float(crit.forward(out0, jnp.asarray(labels)))
        assert after_loss < loss0 * 0.5, (loss0, after_loss)


class TestCheckpointWriter:
    def test_roundtrip_and_tf_reads_our_bundle(self, tmp_path):
        """write_checkpoint output loads back through BOTH our reader and
        tf.train.load_checkpoint (byte-exact tensors + masked-crc32c
        entries the TF runtime verifies)."""
        from bigdl_tpu.utils.tf_checkpoint import write_checkpoint

        rs = np.random.RandomState(0)
        tensors = {"conv/w": rs.randn(3, 3, 2, 4).astype(np.float32),
                   "fc/bias": rs.randn(6).astype(np.float32),
                   "global_step": np.asarray(77, np.int64)}
        prefix = write_checkpoint(str(tmp_path / "out.ckpt"), tensors)
        back = read_checkpoint(prefix)
        for k, v in tensors.items():
            np.testing.assert_array_equal(back[k], v)
        reader = tf.train.load_checkpoint(prefix)
        for k, v in tensors.items():
            np.testing.assert_array_equal(reader.get_tensor(k), v)

    def test_finetune_then_save_checkpoint_tf_compatible(self, tmp_path):
        """Import + fine-tune an unfrozen graph, save_checkpoint(), and
        confirm TF reads back the TRAINED values under the original
        variable names (the round-trip the reference's
        export_tf_checkpoint flow provides)."""
        from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch
        from bigdl_tpu.optim import SGD, Trigger
        from bigdl_tpu.utils.session import Session

        pb, prefix, xv, _ = _build_v1_conv_graph(tmp_path)
        labels = (np.arange(N) % CLASSES).astype(np.int32)
        ds = ArrayDataSet([Sample.from_ndarray(xv[i], labels[i])
                           for i in range(N)]).transform(SampleToMiniBatch(N))
        sess = Session(pb, ["x"], [(N, H, W, C)], checkpoint=prefix)
        sess.train(["out"], ds, nn.CrossEntropyCriterion(),
                   optim_method=SGD(learning_rate=0.3),
                   end_when=Trigger.max_epoch(3))
        out_prefix = sess.save_checkpoint(str(tmp_path / "trained.ckpt"))
        reader = tf.train.load_checkpoint(out_prefix)
        for name in ("conv_w", "conv_b", "fc_w"):
            np.testing.assert_array_equal(
                reader.get_tensor(name),
                np.asarray(sess.params[name]["value"]))
        # and the trained values differ from the original checkpoint
        orig = read_checkpoint(prefix)
        assert np.abs(reader.get_tensor("conv_w") - orig["conv_w"]).max() > 1e-5

    def test_frozen_graph_save_checkpoint_is_loud(self, tmp_path):
        from bigdl_tpu.utils.session import Session

        pb, prefix, xv, _ = _build_v1_conv_graph(tmp_path)
        # freeze by loading without variables? simplest: a const-only graph
        g = tf.Graph()
        with g.as_default():
            x = tf.compat.v1.placeholder(tf.float32, [2, 3], name="x")
            w = tf.constant(np.ones((3, 2), np.float32))
            tf.linalg.matmul(x, w, name="out")
        pb2 = str(tmp_path / "frozen.pb")
        with open(pb2, "wb") as fh:
            fh.write(g.as_graph_def().SerializeToString())
        sess = Session(pb2, ["x"], [(2, 3)])
        sess._construct(["out"])
        with pytest.raises(ValueError, match="no Variables"):
            sess.save_checkpoint(str(tmp_path / "nope.ckpt"))


class TestSummarizeGraph:
    def test_reports_inputs_variables_frames_outputs(self, tmp_path):
        from bigdl_tpu.utils.tensorflow import summarize_graph

        pb, _, _, _ = _build_v1_conv_graph(tmp_path)
        s = summarize_graph(pb)
        assert [i["name"] for i in s["inputs"]] == ["x"]
        assert {v["name"] for v in s["variables"]} == \
            {"conv_w", "conv_b", "fc_w"}
        assert "out" in s["likely_outputs"]
        assert s["ops"]["VariableV2"] == 3


def _build_partitioned_graph(tmp_path, n_parts=2):
    """v1 graph whose fc weight is created under a fixed-size variable
    partitioner: the checkpoint stores 'fc_w' as a full-tensor entry with
    TensorSliceProtos plus per-slice data entries, and the GraphDef holds
    the parts as separate VariableV2 nodes 'fc_w/part_i'."""
    rs = np.random.RandomState(11)
    din, dout = 6, CLASSES
    g = tf.Graph()
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, [N, din], name="x")
        w = tf.compat.v1.get_variable(
            "fc_w", shape=(din, dout),
            partitioner=tf.compat.v1.fixed_size_partitioner(n_parts),
            initializer=tf.compat.v1.random_normal_initializer(
                stddev=0.3, seed=11),
            use_resource=False)
        b = tf.compat.v1.get_variable(
            "fc_b", shape=(dout,),
            initializer=tf.compat.v1.random_normal_initializer(
                stddev=0.1, seed=12),
            use_resource=False)
        y = tf.linalg.matmul(x, tf.convert_to_tensor(w)) + b
        y = tf.identity(y, name="out")
        init = tf.compat.v1.global_variables_initializer()
        saver = tf.compat.v1.train.Saver()
    xv = rs.randn(N, din).astype(np.float32)
    with tf.compat.v1.Session(graph=g) as sess:
        sess.run(init)
        ref, wv = sess.run([y, tf.convert_to_tensor(w)], {x: xv})
        prefix = saver.save(sess, str(tmp_path / "part.ckpt"))
    pb = str(tmp_path / "part_graph.pb")
    with open(pb, "wb") as fh:
        fh.write(g.as_graph_def().SerializeToString())
    return pb, prefix, xv, ref, wv, din


class TestPartitionedVariables:
    def test_partitioned_checkpoint_reassembles(self, tmp_path):
        """BundleEntryProto.slices: the full tensor reassembles from its
        slice entries and matches TF's own loader; the per-part aliases
        carry the slices in order."""
        _, prefix, _, _, wv, din = _build_partitioned_graph(tmp_path)
        ours = read_checkpoint(prefix)
        np.testing.assert_allclose(ours["fc_w"], wv, rtol=1e-6)
        # parity with TF's reader on the full tensor
        reader = tf.train.load_checkpoint(prefix)
        np.testing.assert_allclose(ours["fc_w"],
                                   reader.get_tensor("fc_w"), rtol=1e-6)
        # part aliases stack back to the full tensor (partitioned on dim 0)
        np.testing.assert_allclose(
            np.concatenate([ours["fc_w/part_0"], ours["fc_w/part_1"]],
                           axis=0), wv, rtol=1e-6)

    def test_partitioned_graph_restores_and_finetunes(self, tmp_path):
        """The VERDICT 'done' criterion: a 2-way-partitioned variable
        fixture restores (forward parity vs the TF session) and
        fine-tunes via Session."""
        from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch
        from bigdl_tpu.optim import SGD, Trigger
        from bigdl_tpu.utils.session import Session

        pb, prefix, xv, ref, _, din = _build_partitioned_graph(tmp_path)
        g0, gp0, gs0 = load_tensorflow(pb, ["x"], ["out"], [(N, din)],
                                       checkpoint=prefix)
        out0, _ = g0.apply(gp0, gs0, jnp.asarray(xv))
        np.testing.assert_allclose(np.asarray(out0), ref, rtol=1e-4,
                                   atol=1e-5)

        labels = (np.arange(N) % CLASSES).astype(np.int32)
        samples = [Sample.from_ndarray(xv[i], labels[i]) for i in range(N)]
        ds = ArrayDataSet(samples).transform(SampleToMiniBatch(N))
        sess = Session(pb, ["x"], [(N, din)], checkpoint=prefix)
        crit = nn.CrossEntropyCriterion()
        loss0 = float(crit.forward(jnp.asarray(out0), jnp.asarray(labels)))
        sess.train(["out"], ds, crit, optim_method=SGD(learning_rate=0.5),
                   end_when=Trigger.max_epoch(30))
        out1, _ = sess.model.apply(sess.params, sess.state, jnp.asarray(xv))
        loss1 = float(crit.forward(out1, jnp.asarray(labels)))
        assert loss1 < loss0 * 0.5, (loss0, loss1)


class TestPartitionedAndStringWrite:
    def test_partitioned_write_roundtrips_and_tf_reads(self, tmp_path):
        """VERDICT r4 item 9 (write half): partitioned bundle write —
        differential against real TF's reader AND our own restore."""
        from bigdl_tpu.utils.tf_checkpoint import write_checkpoint

        rs = np.random.RandomState(1)
        full = rs.randn(10, 6).astype(np.float32)
        tensors = {"emb/weights": full,
                   "plain": rs.randn(4).astype(np.float32)}
        prefix = write_checkpoint(str(tmp_path / "part.ckpt"), tensors,
                                  partitions={"emb/weights": 3})
        # our reader reassembles the full tensor and exposes the parts
        back = read_checkpoint(prefix)
        np.testing.assert_array_equal(back["emb/weights"], full)
        np.testing.assert_array_equal(back["emb/weights/part_0"], full[:4])
        np.testing.assert_array_equal(back["emb/weights/part_2"], full[7:])
        np.testing.assert_array_equal(back["plain"], tensors["plain"])
        # real TF reassembles the sliced tensor too
        reader = tf.train.load_checkpoint(prefix)
        np.testing.assert_array_equal(reader.get_tensor("emb/weights"), full)
        np.testing.assert_array_equal(reader.get_tensor("plain"),
                                      tensors["plain"])

    def test_string_tensor_roundtrips_and_tf_reads(self, tmp_path):
        """VERDICT r4 item 9 (DT_STRING half)."""
        from bigdl_tpu.utils.tf_checkpoint import write_checkpoint

        strs = np.array([b"alpha", b"", b"long-" * 40 + b"tail",
                         "unicode-é".encode()], object).reshape(2, 2)
        tensors = {"vocab/words": strs,
                   "num": np.arange(3, dtype=np.int32)}
        prefix = write_checkpoint(str(tmp_path / "str.ckpt"), tensors)
        back = read_checkpoint(prefix)
        assert back["vocab/words"].shape == (2, 2)
        assert [bytes(v) for v in back["vocab/words"].reshape(-1)] == \
            [bytes(v) for v in strs.reshape(-1)]
        reader = tf.train.load_checkpoint(prefix)
        got = reader.get_tensor("vocab/words")
        assert [bytes(v) for v in np.asarray(got).reshape(-1)] == \
            [bytes(v) for v in strs.reshape(-1)]

    def test_tf_written_string_tensor_reads_back(self, tmp_path):
        """Differential the OTHER direction: TF writes DT_STRING, our
        reader parses it (previously skipped as bookkeeping)."""
        from bigdl_tpu.utils.tf_checkpoint import write_checkpoint  # noqa

        with tf.Graph().as_default():
            v = tf.Variable(np.array([b"abc", b"de"], object), name="sv",
                            dtype=tf.string)
            num = tf.Variable(np.float32(3.5), name="nv")
            saver = tf.compat.v1.train.Saver([v, num])
            with tf.compat.v1.Session() as s:
                s.run(tf.compat.v1.global_variables_initializer())
                prefix = saver.save(s, str(tmp_path / "tfstr.ckpt"))
        back = read_checkpoint(prefix)
        assert [bytes(x) for x in back["sv"]] == [b"abc", b"de"]
        assert back["nv"] == np.float32(3.5)

    def test_tf_written_partitioned_string_reads_back(self, tmp_path):
        """TF-written PARTITIONED string variable (slices + DT_STRING at
        once): reassembled instead of crashing in the string fast path."""
        with tf.Graph().as_default():
            with tf.compat.v1.variable_scope(
                    "s", partitioner=tf.compat.v1.fixed_size_partitioner(2)):
                v = tf.compat.v1.get_variable(
                    "words", dtype=tf.string,
                    initializer=tf.constant(["aa", "bb", "cc", "dd"]))
            saver = tf.compat.v1.train.Saver()
            with tf.compat.v1.Session() as s:
                s.run(tf.compat.v1.global_variables_initializer())
                prefix = saver.save(s, str(tmp_path / "pstr.ckpt"))
        back = read_checkpoint(prefix)
        got = [bytes(x) for x in back["s/words"]]
        assert got == [b"aa", b"bb", b"cc", b"dd"]

    def test_write_partitions_validation(self, tmp_path):
        from bigdl_tpu.utils.tf_checkpoint import write_checkpoint

        t = {"a": np.arange(6, dtype=np.float32)}
        with pytest.raises(ValueError, match="not in tensors"):
            write_checkpoint(str(tmp_path / "x.ckpt"), t,
                             partitions={"typo": 2})
        with pytest.raises(ValueError, match=">= 1"):
            write_checkpoint(str(tmp_path / "x.ckpt"), t,
                             partitions={"a": -1})
