"""Load a frozen TensorFlow GraphDef, fine-tune it, and serve it
(reference: example/tensorflow + example/loadmodel + utils/tf/Session.scala).

Without --pb, first exports a small convnet as a frozen GraphDef so the
example is self-contained; then imports it through Session, fine-tunes on
synthetic data, and runs batched prediction.

    python examples/tf_loadmodel.py [--pb model.pb --input input --output out]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def export_demo_pb(path, shape):
    import jax

    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils import save_tensorflow

    m = nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, -1, -1), nn.ReLU(),
        nn.SpatialMaxPooling(2, 2),
        nn.Flatten(),
        nn.Linear(8 * (shape[1] // 2) * (shape[2] // 2), 10), nn.SoftMax())
    p, s, _ = m.build(jax.random.PRNGKey(0), shape)
    save_tensorflow(m, p, s, path, shape)
    return list(m.children.values())[-1].name  # the Softmax output node


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--pb", default=None, help="frozen GraphDef path")
    ap.add_argument("--input", default="input")
    ap.add_argument("--output", default=None)
    ap.add_argument("--epochs", type=int, default=3)
    args = ap.parse_args(argv)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, MiniBatch
    from bigdl_tpu.optim import SGD, Trigger
    from bigdl_tpu.utils import Session

    shape = (16, 16, 16, 3)
    pb, out_name = args.pb, args.output
    if pb is None:
        pb = os.path.join(tempfile.mkdtemp(), "demo.pb")
        out_name = export_demo_pb(pb, shape)
        print(f"exported demo GraphDef to {pb} (output node {out_name!r})")

    sess = Session(pb, [args.input], [shape])
    rs = np.random.RandomState(0)
    x = rs.rand(*shape).astype(np.float32)
    y = rs.randint(0, 10, shape[0])

    before = sess.predict([out_name], x)
    print(f"imported graph predicts {before.shape}; fine-tuning...")

    # SoftMax output -> train against NLL on log-probs via CrossEntropy on
    # the probabilities' logs: use ClassNLL with log_prob_as_input=False
    crit = nn.ClassNLLCriterion(log_prob_as_input=False)
    sess.train([out_name], DataSet.array([MiniBatch(x, y)]), crit,
               optim_method=SGD(learning_rate=0.1),
               end_when=Trigger.max_epoch(args.epochs))
    after = sess.predict([out_name], x)
    acc = float(np.mean(np.argmax(after, -1) == y))
    print(f"post-finetune train accuracy {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
