"""LeNet-5 local training (reference: example/lenetLocal + models/lenet/Train.scala).

Trains on real MNIST idx files if --data-dir holds them, else on synthetic
digits, using the LocalOptimizer API end-to-end (checkpoint + validation).
No MNIST download in this environment: `python tools/gen_mnist.py --out
data/mnist` writes real-format idx files (see its docstring); the
full-convergence DistriOptimizer run lives in examples/train_mnist.py.

    python examples/lenet_local.py [--data-dir data/mnist] [--epochs 1]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def to_dataset(x, y, batch_size):
    from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch

    samples = [Sample.from_ndarray(xi, np.int32(yi)) for xi, yi in zip(x, y)]
    return ArrayDataSet(samples).transform(SampleToMiniBatch(batch_size))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--checkpoint", default=None)
    args = ap.parse_args(argv)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import LocalOptimizer, SGD, Top1Accuracy, Trigger

    if args.data_dir:
        from bigdl_tpu.dataset import load_mnist

        x, y = load_mnist(args.data_dir, "train")
        xt, yt = load_mnist(args.data_dir, "test")
    else:
        print("no --data-dir: training on synthetic digits")
        rs = np.random.RandomState(0)
        x = rs.rand(512, 28, 28, 1).astype("float32")
        y = rs.randint(0, 10, 512)
        xt, yt = x[:128], y[:128]

    model = LeNet5(10)
    optimizer = LocalOptimizer(
        model, to_dataset(x, y, args.batch_size), nn.ClassNLLCriterion(),
        optim_method=SGD(learning_rate=0.05, momentum=0.9),
        end_trigger=Trigger.max_epoch(args.epochs))
    optimizer.set_validation(Trigger.every_epoch(),
                             to_dataset(xt, yt, args.batch_size),
                             [Top1Accuracy()])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, Trigger.every_epoch())
    optimizer.optimize()
    for res in optimizer.validate():
        print("validation:", res)


if __name__ == "__main__":
    main()
