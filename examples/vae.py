"""Variational autoencoder on MNIST-shaped images.

Reference: models/autoencoder (the plain AE entry point) extended with the
reference's own VAE building blocks — nn/GaussianSampler.scala
(reparameterised sampling) and nn/KLDCriterion.scala — wired the TPU way:
one jitted step computes reconstruction + KL and their gradients.

    python examples/vae.py [--data-dir MNIST_DIR] [--epochs 3]
"""

from __future__ import annotations

import argparse

import numpy as np


def build_vae(latent: int = 16):
    import bigdl_tpu.nn as nn

    encoder = nn.Sequential(
        nn.Flatten(),
        nn.Linear(784, 256), nn.ReLU(),
        nn.Linear(256, 2 * latent),  # [mean | log_var]
    )
    decoder = nn.Sequential(
        nn.Linear(latent, 256), nn.ReLU(),
        nn.Linear(256, 784), nn.Sigmoid(),
    )
    return encoder, decoder


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--latent", type=int, default=16)
    ap.add_argument("--kl-weight", type=float, default=1.0)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.core.table import Table
    from bigdl_tpu.optim import Adam

    if args.data_dir:
        from bigdl_tpu.dataset import load_mnist

        # raw pixels (the loader's default mean/std-normalization would put
        # targets outside [0, 1] and break the BCE objective)
        x, _ = load_mnist(args.data_dir, "train", normalize=False)
        x = x.reshape(-1, 784).astype("float32") / 255.0
    else:
        print("no --data-dir: synthetic blob images")
        rs = np.random.RandomState(0)
        centers = rs.rand(10, 784).astype("float32")
        x = np.clip(centers[rs.randint(0, 10, 2048)]
                    + 0.1 * rs.randn(2048, 784).astype("float32"), 0, 1)

    latent = args.latent
    encoder, decoder = build_vae(latent)
    e_params, e_state, _ = encoder.build(jax.random.PRNGKey(0), (args.batch_size, 784))
    d_params, d_state, _ = decoder.build(jax.random.PRNGKey(1), (args.batch_size, latent))
    sampler = nn.GaussianSampler()
    bce = nn.BCECriterion(size_average=False)
    kld = nn.KLDCriterion(size_average=False)
    optim = Adam(learning_rate=1e-3)
    opt_state = optim.init({"enc": e_params, "dec": d_params})

    @jax.jit
    def step(params, opt_state, xb, rng):
        def loss_fn(p):
            h, _ = encoder.apply(p["enc"], e_state, xb)
            mean, log_var = h[:, :latent], h[:, latent:]
            z, _ = sampler.apply({}, {}, Table(mean, log_var), rng=rng)
            recon, _ = decoder.apply(p["dec"], d_state, z)
            rec_loss = bce.forward(recon, xb) / xb.shape[0]
            kl_loss = kld.forward(Table(mean, log_var)) / xb.shape[0]
            return rec_loss + args.kl_weight * kl_loss, (rec_loss, kl_loss)

        (loss, (rec, kl)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = optim.step(grads, params, opt_state)
        return new_params, new_opt, loss, rec, kl

    params = {"enc": e_params, "dec": d_params}
    key = jax.random.PRNGKey(42)
    n = x.shape[0] - x.shape[0] % args.batch_size
    if n == 0 or args.epochs <= 0:
        raise ValueError(f"nothing to train: {x.shape[0]} samples, "
                         f"batch {args.batch_size}, {args.epochs} epochs")
    loss = rec = kl = None
    for epoch in range(args.epochs):
        # permute the FULL range then trim, so the remainder tail rotates
        # through epochs instead of never being sampled
        perm = np.random.RandomState(epoch).permutation(x.shape[0])[:n]
        for i in range(0, n, args.batch_size):
            xb = jnp.asarray(x[perm[i:i + args.batch_size]])
            key, sub = jax.random.split(key)
            params, opt_state, loss, rec, kl = step(params, opt_state, xb, sub)
        print(f"epoch {epoch + 1}: loss={float(loss):.4f} "
              f"rec={float(rec):.4f} kl={float(kl):.4f}")
    return float(loss), float(kl)


if __name__ == "__main__":
    main()
