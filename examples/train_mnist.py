"""BASELINE config 1: LeNet-5 on MNIST-format data, trained to >=99% test
accuracy on the TPU, end-to-end through DistriOptimizer with checkpoints
and TensorBoard summaries.

Reference: models/lenet/Train.scala (DataSet.array(load(trainData)) ->
Optimizer(...).setValidation(EveryEpoch, Top1Accuracy)
.setCheckpoint(...).setEndWhen(MaxEpoch(n)).optimize()).

Data: `python tools/gen_mnist.py --out data/mnist` writes real-format idx
files derived from the real sklearn handwritten digits (see that script's
docstring for exactly what is and isn't real here); the loader below is
the production `bigdl_tpu.dataset.load_mnist`, unchanged from what would
parse the genuine files.

The full train set is 47 MB, so batches are uploaded to the device ONCE
and stay resident across epochs (standard practice for HBM-resident
datasets); epoch order still reshuffles at MiniBatch granularity.

    python examples/train_mnist.py --data-dir data/mnist --epochs 12 \
        --checkpoint /tmp/lenet_ckpt --summary /tmp/lenet_summary

Prints one JSON line with {test_acc, wall_s, img_per_s, epochs}.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def batched_dataset(x, y, batch_size, device_resident, drop_last=False):
    """Pre-batch (and optionally pre-upload) the whole set.  drop_last
    only for the TRAIN split (one static shape for the jitted step); the
    eval split keeps its ragged tail — test accuracy must cover all
    10,000 images (the ragged batch costs one extra eval compile)."""
    import jax.numpy as jnp

    from bigdl_tpu.dataset import ArrayDataSet, MiniBatch

    items = []
    end = len(x) - batch_size + 1 if drop_last else len(x)
    for i in range(0, end, batch_size):
        bx, by = x[i:i + batch_size], y[i:i + batch_size]
        if device_resident:
            bx, by = jnp.asarray(bx), jnp.asarray(by)
        items.append(MiniBatch(bx, by))
    return ArrayDataSet(items)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default="data/mnist")
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--decay-epoch", type=int, default=12,
                    help="epoch at which lr drops 10x (classic step decay)")
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--summary", default=None,
                    help="TensorBoard log dir (TrainSummary+ValidationSummary)")
    ap.add_argument("--host-batches", action="store_true",
                    help="keep batches on host (per-step upload path)")
    args = ap.parse_args(argv)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import load_mnist
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import (DistriOptimizer, SGD, Top1Accuracy, Trigger)
    from bigdl_tpu.utils.summary import TrainSummary, ValidationSummary

    x, y = load_mnist(args.data_dir, "train")
    xt, yt = load_mnist(args.data_dir, "test")
    print(f"train {x.shape} test {xt.shape}")

    resident = not args.host_batches
    train_ds = batched_dataset(x, y, args.batch_size, resident,
                               drop_last=True)
    val_ds = batched_dataset(xt, yt, args.batch_size, resident)

    import jax.numpy as jnp

    from bigdl_tpu.optim.schedules import EpochDecay

    model = LeNet5(10)
    # reference models/lenet/Train.scala: SGD + momentum, NLL on log-probs;
    # classic step decay: 10x drop at --decay-epoch
    de = args.decay_epoch
    sched = EpochDecay(lambda e: (e >= de).astype(jnp.float32)) \
        if de and de < args.epochs else None
    optimizer = DistriOptimizer(
        model, train_ds, nn.ClassNLLCriterion(),
        optim_method=SGD(learning_rate=args.lr, momentum=0.9,
                         weight_decay=1e-4, schedule=sched),
        end_trigger=Trigger.max_epoch(args.epochs))
    optimizer.set_validation(Trigger.every_epoch(), val_ds, [Top1Accuracy()])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, Trigger.every_epoch())
    if args.summary:
        optimizer.set_train_summary(TrainSummary(args.summary, "lenet"))
        optimizer.set_val_summary(ValidationSummary(args.summary, "lenet"))

    t0 = time.time()
    optimizer.optimize()
    wall = time.time() - t0

    results = optimizer.validate()
    acc = float(results[0].result()[0])
    n_img = (len(x) // args.batch_size) * args.batch_size * args.epochs
    out = {"config": "lenet5_mnist", "test_acc": round(acc, 5),
           "epochs": args.epochs, "wall_s": round(wall, 1),
           "img_per_s": round(n_img / wall, 1),
           "target": 0.99, "met": acc >= 0.99}
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
