"""Train an SSD-style detection head with ROI-aware augmentation.

Reference flow: transform/vision/image/label/roi/ (RoiLabel + geometry
transforms + the SSD random-crop sampler) feeding a detection model; the
MultiBox matching/loss glue lives in nn/detection.py here so the whole
loop is runnable in-core.

  python examples/ssd_detection_training.py
"""

import numpy as np


def synth_features(n, grid=4, classes=3, seed=0):
    """Images with one colored box each; RoiLabels in pixel space."""
    from bigdl_tpu.vision.image import ImageFeature
    from bigdl_tpu.vision.roi import RoiLabel

    rs = np.random.RandomState(seed)
    feats = []
    for _ in range(n):
        img = np.zeros((32, 32, 3), np.float32)
        c = rs.randint(classes)
        gx, gy = rs.randint(grid), rs.randint(grid)
        x1, y1 = gx * 8 + 1, gy * 8 + 1
        img[y1:y1 + 6, x1:x1 + 6, c] = 1.0
        label = RoiLabel(np.asarray([float(c)]),
                         np.asarray([[x1, y1, x1 + 6.0, y1 + 6.0]]))
        feats.append(ImageFeature(image=img, label=label))
    return feats


def main():
    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.detection import MultiBoxCriterion
    from bigdl_tpu.vision.roi import (RoiHFlip, RoiImageToBatch,
                                      RoiNormalize)

    grid, classes = 4, 3
    # augmentation chain: normalize boxes, random horizontal flip mirrored
    # on the labels (the RandomSampler crop zoo also chains here for
    # variable-size datasets; this demo keeps static 32x32 images)
    feats = synth_features(96, grid, classes)
    aug = RoiNormalize()
    flip = RoiHFlip(normalized=True)
    rs = np.random.RandomState(7)
    for f in feats:
        aug(f)
        if rs.rand() < 0.5:
            f.image = f.image[:, ::-1].copy()
            flip(f)

    # priors: one square per grid cell
    cx, cy = np.meshgrid((np.arange(grid) + 0.5) / grid,
                         (np.arange(grid) + 0.5) / grid)
    c = np.stack([cx.ravel(), cy.ravel()], 1)
    priors = np.concatenate([c - 0.15, c + 0.15], 1).astype(np.float32)
    m = priors.shape[0]

    head = nn.Sequential(
        nn.SpatialConvolution(3, 16, 3, 3, 1, 1, 1, 1), nn.ReLU(),
        nn.SpatialConvolution(16, 32, 3, 3, 8, 8, 1, 1), nn.ReLU(),
        nn.ConcatTable(
            nn.Sequential(nn.SpatialConvolution(32, 4, 1, 1),
                          nn.Reshape([m, 4], batch_mode=True)),
            nn.Sequential(nn.SpatialConvolution(32, classes + 1, 1, 1),
                          nn.Reshape([m, classes + 1], batch_mode=True))))
    params, state, _ = head.build(jax.random.PRNGKey(0), (8, 32, 32, 3))
    crit = MultiBoxCriterion(priors)

    def loss_fn(p, x, t):
        out, _ = head.apply(p, state, x, training=True)
        return crit.forward(out, t)

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    batches = list(RoiImageToBatch(16, n_max_boxes=4)(feats))
    lr, l0 = 0.1, None
    for epoch in range(30):
        for b in batches:
            lv, g = grad_fn(params, jnp.asarray(b.input),
                            jnp.asarray(b.target))
            params = jax.tree_util.tree_map(lambda p, gg: p - lr * gg,
                                            params, g)
            if l0 is None:
                l0 = float(lv)
    l1 = float(lv)
    print(f"multibox loss: {l0:.3f} -> {l1:.3f}")
    assert l1 < l0 * 0.5, (l0, l1)


if __name__ == "__main__":
    main()
