"""Autoregressive generation through the prefill/decode engine
(docs/serving.md "Autoregressive generation").

Builds a small TransformerLM, stands up a `GenerationEngine` (or a full
`ServingRuntime` with `--runtime`, so batch predict and generation share
one registry), and streams a handful of continuous-batched completions —
printing per-request TTFT / ms-per-token and the engine's executable
count, which stays at `len(buckets) x 2` no matter how many requests run.

With real trained weights, point `--ckpt` at a trainer checkpoint root:
the newest committed `ckpt_<step>` is registered through the same
hot-swap path a production weight push uses.

    python examples/generate.py [--prompts 8] [--max-new 24] [--runtime]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab-size", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--prompts", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=24)
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--top-k", type=int, default=40)
    ap.add_argument("--buckets", type=int, nargs="+", default=[32, 128])
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--ckpt", default=None,
                    help="trainer checkpoint root or ckpt_<step> dir")
    ap.add_argument("--runtime", action="store_true",
                    help="attach to a ServingRuntime instead of standalone")
    args = ap.parse_args(argv)

    import jax

    from bigdl_tpu.generation import GenerationEngine
    from bigdl_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab_size=args.vocab_size,
                          hidden_size=args.hidden, n_layer=args.layers,
                          n_head=4, max_len=1024, use_flash=False)
    params, _ = model.init((1, 16), rng=jax.random.PRNGKey(0))

    common = dict(buckets=tuple(args.buckets), slots=args.slots,
                  max_new_tokens=args.max_new,
                  temperature=args.temperature, top_k=args.top_k)
    rt = None
    if args.runtime:
        from bigdl_tpu.serving import ServingRuntime

        rt = ServingRuntime(model, params, buckets=(1, 8),
                            example_input=np.zeros((1, 8), np.int32))
        eng = rt.enable_generation(**common)
    else:
        eng = GenerationEngine(model, params, **common)

    if args.ckpt:
        eng.registry.register_from_checkpoint(args.ckpt)

    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, args.vocab_size,
                           size=int(rng.randint(3, 12)))
               for _ in range(args.prompts)]
    futs = [eng.submit(p) for p in prompts]  # all in flight at once
    for p, f in zip(prompts, futs):
        r = f.result(timeout=300)
        toks = [int(t) for t in r.tokens]
        print(f"[{r.meta['cid']}] prompt={[int(t) for t in p[:6]]}... "
              f"-> {toks[:8]}{'...' if len(toks) > 8 else ''} "
              f"({r.meta['finish_reason']}, ttft {r.meta['ttft_ms']}ms, "
              f"{r.meta['ms_per_token']}ms/token)")

    snap = eng.export_metrics()
    print(f"\n{snap['tokens_generated']} tokens over "
          f"{snap['requests_completed']} requests; ms/token "
          f"p50={snap['ms_per_token']['p50']} "
          f"p99={snap['ms_per_token']['p99']}; "
          f"{eng.compile_count()} executables "
          f"(budget {2 * len(args.buckets)})")
    (rt or eng).close()


if __name__ == "__main__":
    main()
