"""DataFrame ML pipeline (reference: example/MLPipeline + example/dlframes:
DLClassifier over Spark-ML columns; pandas is the TPU-side dataframe).

Trains a DLClassifier on a toy two-moons-ish frame, appends predictions
with the fitted DLClassifierModel, and runs DLImageReader +
DLImageTransformer over a directory of generated images.

    python examples/ml_pipeline.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main(argv=None):
    import pandas as pd

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dlframes import (DLClassifier, DLImageReader,
                                    DLImageTransformer)
    from bigdl_tpu.vision import CenterCropper, ChannelNormalize

    # --- tabular: DLClassifier.fit over (features, label) columns ---------
    rs = np.random.RandomState(0)
    n = 512
    labels = rs.randint(0, 2, n)  # 0-based class ids (documented delta from
    # the reference's 1-based Spark-ML convention)
    feats = rs.randn(n, 4).astype(np.float32) + labels[:, None] * 1.5
    df = pd.DataFrame({"features": [f for f in feats], "label": labels})

    from bigdl_tpu.optim import SGD

    model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2),
                          nn.LogSoftMax())
    clf = (DLClassifier(model, nn.ClassNLLCriterion(), [4])
           .set_batch_size(64).set_max_epoch(5)
           .set_optim_method(SGD(learning_rate=0.1)))
    fitted = clf.fit(df)
    out = fitted.transform(df)
    acc = float(np.mean(out["prediction"].to_numpy() == df["label"].to_numpy()))
    print(f"DLClassifier train accuracy: {acc:.3f}")

    # --- images: DLImageReader -> DLImageTransformer ----------------------
    img_dir = tempfile.mkdtemp()
    from PIL import Image

    for i in range(4):
        arr = rs.randint(0, 255, (20, 24, 3), dtype=np.uint8)
        Image.fromarray(arr).save(os.path.join(img_dir, f"img_{i}.png"))
    frame = DLImageReader.read_images(img_dir)
    frame = DLImageTransformer(
        CenterCropper(16, 16) >> ChannelNormalize((127,) * 3, (64,) * 3),
        output_col="normalized").transform(frame)
    print(f"image frame: {len(frame)} rows, normalized shape "
          f"{frame.iloc[0]['normalized'].shape}")
    return acc


if __name__ == "__main__":
    main()
