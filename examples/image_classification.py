"""ResNet image classification with the vision pipeline (reference:
example/imageclassification + models/resnet/Train.scala, cifar10 path).

Trains ResNet-20 on CIFAR-10 when --data-dir holds the python batches,
else on synthetic images, with the reference's augmentation chain
(random crop + flip + channel normalize) expressed as FeatureTransformers.

    python examples/image_classification.py [--data-dir cifar-10-batches-py]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--depth", type=int, default=20)
    ap.add_argument("--samples", type=int, default=256,
                    help="synthetic sample count when no --data-dir")
    args = ap.parse_args(argv)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models import resnet_cifar
    from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger
    from bigdl_tpu.vision import (ChannelNormalize, Expand, Flip, ImageFeature,
                                  RandomCropper, RandomTransformer)

    if args.data_dir:
        from bigdl_tpu.dataset import load_cifar10

        # normalize=False: the augmentation chain below ends with
        # ChannelNormalize, which must see raw 0-255 pixels
        x, y = load_cifar10(args.data_dir, "train", normalize=False)
        x = x.astype(np.float32)
    else:
        rs = np.random.RandomState(0)
        y = rs.randint(0, 10, args.samples)
        # class-dependent mean shift so the synthetic run actually learns
        x = rs.rand(args.samples, 32, 32, 3).astype(np.float32) * 60 + 100
        x += y[:, None, None, None] * 2.0

    # the reference cifar chain: pad+random crop 32, random hflip, normalize
    # (models/resnet/Train.scala + dataset/image/*)
    augment = (Expand(max_ratio=1.25, means=(0, 0, 0))
               >> RandomCropper(32, 32)
               >> RandomTransformer(Flip(p=1.0), 0.5)
               >> ChannelNormalize((125.3, 123.0, 113.9), (63.0, 62.1, 66.7)))

    def to_sample(args_):
        xi, yi = args_
        feat = augment(ImageFeature(xi))
        return Sample.from_ndarray(feat.image, np.int32(yi))

    samples = [to_sample(a) for a in zip(x, y)]
    ds = ArrayDataSet(samples).transform(SampleToMiniBatch(args.batch_size))

    model = resnet_cifar(args.depth, 10)  # ends in LogSoftMax -> NLL loss
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                         optim_method=SGD(learning_rate=0.05, momentum=0.9,
                                          weight_decay=1e-4),
                         end_trigger=Trigger.max_epoch(args.epochs))
    opt.optimize()
    print(f"final loss {opt._driver_state['loss']:.4f}")
    return opt._driver_state["loss"]


if __name__ == "__main__":
    main()
