"""Tree-LSTM sentiment classification (reference: example/treeLSTMSentiment).

Builds BinaryTreeLSTM + a root classifier over synthetic labeled parse
trees (the real SST pipeline needs the corpus download the reference also
leaves to the user) and trains to separate two sentiment classes whose
word embeddings are drawn from shifted distributions.

    python examples/tree_lstm_sentiment.py [--steps 60]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def random_tree(rs, n_words, n_nodes):
    """A random full binary parse over `n_words` leaves, arrays padded to
    n_nodes (children -1 on leaves/padding; word -1 on internal nodes)."""
    left = -np.ones(n_nodes, np.int32)
    right = -np.ones(n_nodes, np.int32)
    word = -np.ones(n_nodes, np.int32)
    word[:n_words] = np.arange(n_words)
    avail = list(range(n_words))
    nxt = n_words
    while len(avail) > 1:
        i = rs.randint(len(avail) - 1)
        l, r = avail.pop(i), avail.pop(i)
        left[nxt], right[nxt] = l, r
        avail.insert(i, nxt)
        nxt += 1
    return left, right, word, nxt - 1  # root index


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--hidden", type=int, default=16)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.core.table import Table
    from bigdl_tpu.optim import Adagrad, TreeNNAccuracy

    n_words, n_nodes, dim, classes = 6, 11, 8, 2
    rs = np.random.RandomState(0)

    def make_batch(b):
        embs, lefts, rights, words, labels, roots = [], [], [], [], [], []
        for _ in range(b):
            label = rs.randint(classes)
            # class-dependent embedding shift = the learnable signal
            embs.append(rs.randn(n_words, dim).astype(np.float32)
                        + (label * 2 - 1) * 0.6)
            l, r, w, root = random_tree(rs, n_words, n_nodes)
            lefts.append(l); rights.append(r); words.append(w)
            labels.append(label); roots.append(root)
        return (np.stack(embs), np.stack(lefts), np.stack(rights),
                np.stack(words), np.asarray(labels), np.asarray(roots))

    tree_lstm = nn.BinaryTreeLSTM(dim, args.hidden)
    head = nn.Linear(args.hidden, classes)
    p1, s1, _ = tree_lstm.build(jax.random.PRNGKey(0),
                                Table((args.batch, n_words, dim),
                                      (args.batch, n_nodes), (args.batch, n_nodes)))
    p2, s2, _ = head.build(jax.random.PRNGKey(1), (args.batch, args.hidden))
    params = {"tree": p1, "head": p2}
    crit = nn.CrossEntropyCriterion()
    optim = Adagrad(learning_rate=0.1)
    opt_state = optim.init(params)

    @jax.jit
    def step(params, opt_state, emb, left, right, word, label, root):
        def loss_fn(p):
            hid, _ = tree_lstm.apply(p["tree"], s1,
                                     Table(emb, Table(left, right, word)))
            root_h = hid[jnp.arange(hid.shape[0]), root]
            logits, _ = head.apply(p["head"], s2, root_h)
            return crit.forward(logits, label), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt_state = optim.step(grads, params, opt_state)
        return new_params, new_opt_state, loss, logits

    acc_metric = TreeNNAccuracy()
    for it in range(args.steps):
        emb, left, right, word, label, root = make_batch(args.batch)
        params, opt_state, loss, logits = step(
            params, opt_state, emb, left, right, word, label, root)
        if (it + 1) % 20 == 0:
            print(f"step {it + 1}: loss {float(loss):.4f}")

    # final eval with TreeNNAccuracy (per-example root indices via Table)
    emb, left, right, word, label, root = make_batch(64)
    hid, _ = tree_lstm.apply(params["tree"], s1,
                             Table(jnp.asarray(emb),
                                   Table(jnp.asarray(left), jnp.asarray(right),
                                         jnp.asarray(word))))
    logits, _ = head.apply(params["head"], s2, hid)  # (B, n_nodes, C)
    correct, count = acc_metric.batch(logits,
                                      Table(jnp.asarray(label), jnp.asarray(root)))
    acc = float(correct) / float(count)
    print(f"root accuracy: {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
