"""Train a TransformerLM with data + pipeline parallelism through the
public DistriOptimizer builder.

Beyond-reference capability (survey §2.10 records pipeline parallelism
absent in BigDL).  The block stack is partitioned over the 'pipeline' mesh
axis and executed as an interleaved microbatch schedule
(parallel/pipeline.py); embed / final-norm / head stay data-parallel.
Runs on the 8-virtual-device CPU mesh out of the box:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/pipelined_lm.py
"""

import numpy as np

import jax
from jax.sharding import PartitionSpec as P


def main():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.core.engine import AXIS_DATA, AXIS_PIPELINE, Engine
    from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.optim import Adam, DistriOptimizer, Trigger
    from bigdl_tpu.parallel import ShardingRules

    n_dev = jax.device_count()
    if n_dev < 2 or n_dev % 2:
        raise SystemExit(
            f"pipelined_lm needs an even device count >= 2 (got {n_dev}); "
            f"run with JAX_PLATFORMS=cpu "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8")
    pp = 4 if n_dev % 4 == 0 else 2
    dp = n_dev // pp
    mesh = Engine.build_mesh(**{AXIS_DATA: dp, AXIS_PIPELINE: pp})
    print(f"mesh: data={dp} x pipeline={pp}")

    vocab, seq_len, batch = 256, 32, 8 * dp
    model = TransformerLM(vocab_size=vocab, hidden_size=64, n_layer=2 * pp,
                          n_head=4, scan_layers=True,
                          pipeline_axis=AXIS_PIPELINE,
                          pipeline_microbatches=pp,
                          pipeline_interleave=True)

    # synthetic next-token data with learnable structure (periodic tokens)
    rs = np.random.RandomState(0)
    base = rs.randint(0, vocab, 64)
    stream = np.tile(base, 50)
    samples = []
    for i in range(0, len(stream) - seq_len - 1, seq_len):
        samples.append(Sample.from_ndarray(
            stream[i:i + seq_len].astype(np.int32),
            stream[i + 1:i + seq_len + 1].astype(np.int32)))
    ds = ArrayDataSet(samples).transform(SampleToMiniBatch(batch))

    rules = ShardingRules().add(r"^blocks/", P(AXIS_PIPELINE))
    opt = DistriOptimizer(
        model, ds, nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), True),
        optim_method=Adam(learning_rate=3e-3),
        mesh=mesh, sharding_rules=rules,
        end_trigger=Trigger.max_epoch(3))
    opt.optimize()
    print(f"final loss: {opt._driver_state['loss']:.4f} "
          f"(uniform would be {np.log(vocab):.4f})")
    assert opt._driver_state["loss"] < np.log(vocab)


if __name__ == "__main__":
    main()
