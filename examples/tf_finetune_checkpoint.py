"""Fine-tune an UNFROZEN TensorFlow graph from its checkpoint.

Reference flow: TensorflowLoader binds VariableV2 endpoints to checkpoint
values and Session trains the imported graph
(utils/tf/TensorflowLoader.scala:456, utils/tf/Session.scala,
scripts/export_tf_checkpoint.py).  Here the checkpoint is decoded
host-side by the framework's own tensor-bundle reader
(bigdl_tpu/utils/tf_checkpoint.py) — no TF runtime needed to LOAD; this
example only uses TF to CREATE the fixture.

  python examples/tf_finetune_checkpoint.py
"""

import os
import tempfile

import numpy as np


def make_fixture(workdir):
    """A tiny unfrozen classifier graph + v2-format checkpoint."""
    import tensorflow as tf

    rs = np.random.RandomState(0)
    g = tf.Graph()
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, [16, 8], name="x")
        w1 = tf.compat.v1.Variable(rs.randn(8, 16).astype(np.float32) * 0.3,
                                   name="w1", use_resource=False)
        b1 = tf.compat.v1.Variable(np.zeros(16, np.float32), name="b1",
                                   use_resource=False)
        w2 = tf.compat.v1.Variable(rs.randn(16, 3).astype(np.float32) * 0.3,
                                   name="w2", use_resource=False)
        h = tf.nn.relu(tf.linalg.matmul(x, w1) + b1)
        tf.nn.log_softmax(tf.linalg.matmul(h, w2), name="out")
        init = tf.compat.v1.global_variables_initializer()
        saver = tf.compat.v1.train.Saver()
    with tf.compat.v1.Session(graph=g) as sess:
        sess.run(init)
        prefix = saver.save(sess, os.path.join(workdir, "model.ckpt"))
    pb = os.path.join(workdir, "graph.pb")
    with open(pb, "wb") as fh:
        fh.write(g.as_graph_def().SerializeToString())
    return pb, prefix


def main():
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.optim import SGD, Trigger
    from bigdl_tpu.utils.session import Session

    workdir = tempfile.mkdtemp(prefix="tf_finetune_")
    pb, prefix = make_fixture(workdir)

    # synthetic 3-class task
    rs = np.random.RandomState(1)
    centers = rs.randn(3, 8) * 2
    ys = (np.arange(64) % 3).astype(np.int32)
    xs = (centers[ys] + rs.randn(64, 8) * 0.4).astype(np.float32)
    ds = ArrayDataSet([Sample.from_ndarray(x, y) for x, y in zip(xs, ys)]
                      ).transform(SampleToMiniBatch(16))

    # checkpoint= restores every graph Variable as a trainable parameter
    sess = Session(pb, ["x"], [(16, 8)], checkpoint=prefix)
    model = sess.train(["out"], ds, nn.ClassNLLCriterion(),
                       optim_method=SGD(learning_rate=0.5),
                       end_when=Trigger.max_epoch(15))
    out, _ = model.apply(sess.params, sess.state, jnp.asarray(xs[:16]))
    acc = float((np.argmax(np.asarray(out), -1) == ys[:16]).mean())
    print(f"fine-tuned accuracy on the training slice: {acc:.2f}")
    assert acc >= 0.9, acc


if __name__ == "__main__":
    main()
