"""Keras-style compile/fit on MNIST (reference: example/keras — the
Keras-1.2.2-compatible API of nn/keras/Topology.scala).

Runs on real MNIST idx files when --data-dir is given, else synthetic
digits; shows compile/fit/evaluate/predict plus TensorBoard scalars.

    python examples/keras_mnist.py [--data-dir ~/mnist] [--epochs 2]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--samples", type=int, default=512)
    args = ap.parse_args(argv)

    from bigdl_tpu import keras

    if args.data_dir:
        from bigdl_tpu.dataset import load_mnist

        # the loader already mean/std-normalizes (normalize=True default)
        x, y = load_mnist(args.data_dir, "train")
        x = x.astype(np.float32).reshape(-1, 28, 28, 1)
    else:
        rs = np.random.RandomState(0)
        y = rs.randint(0, 10, args.samples)
        x = rs.rand(args.samples, 28, 28, 1).astype(np.float32) * 0.1
        for i, yi in enumerate(y):  # a learnable bright patch per class
            x[i, 2 + yi * 2: 6 + yi * 2, 4:24] += 0.9

    model = keras.Sequential(
        keras.Convolution2D(16, 3, 3, activation="relu",
                            input_shape=(28, 28, 1)),
        keras.MaxPooling2D((2, 2)),
        keras.Flatten(),
        keras.Dense(64, activation="relu"),
        keras.Dropout(0.25),
        keras.Dense(10),  # logits: sparse_categorical_crossentropy fuses
        # log_softmax + NLL (CrossEntropyCriterion)
    )
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.set_tensorboard(tempfile.mkdtemp(), "keras_mnist")

    split = int(0.9 * len(x))
    model.fit(x[:split], y[:split], batch_size=args.batch_size,
              nb_epoch=args.epochs, validation_data=(x[split:], y[split:]))
    results = model.evaluate(x[split:], y[split:], batch_size=args.batch_size)
    for name, value in results:
        print(f"{name}: {value:.4f}")
    preds = model.predict_classes(x[:8])
    print("sample predictions:", preds, "labels:", y[:8])
    return dict(results)


if __name__ == "__main__":
    main()
