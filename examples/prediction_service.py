"""Concurrent model serving (reference: optim/PredictionService.scala +
example/udfpredictor).

Builds a trained-ish LeNet, stands up a PredictionService pool, and fires
concurrent requests at it.

    python examples/prediction_service.py
"""

import concurrent.futures
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.optim import PredictionService

    model = LeNet5(10)
    params, state, _ = model.build(jax.random.PRNGKey(0), (1, 28, 28, 1))
    service = PredictionService(model, params, state, concurrency=2)

    rs = np.random.RandomState(0)
    batches = [rs.rand(4, 28, 28, 1).astype("float32") for _ in range(8)]
    with concurrent.futures.ThreadPoolExecutor(4) as pool:
        results = list(pool.map(service.predict, batches))
    for i, r in enumerate(results):
        print(f"request {i}: output {np.asarray(r).shape}, "
              f"pred {np.asarray(r).argmax(-1).tolist()}")


if __name__ == "__main__":
    main()
