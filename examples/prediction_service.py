"""Concurrent model serving on the micro-batching runtime.

Reference: optim/PredictionService.scala + example/udfpredictor.  The
reference pools module clones and runs every request alone; here 64
concurrent single-image requests coalesce into a handful of bucketed
fixed-shape batches (one jitted forward per bucket — watch the
`batches` / `batch_occupancy` metrics), a checkpoint hot-swaps under
load without a dropped request, and the admission queue rejects
gracefully when overloaded.

    python examples/prediction_service.py
"""

import concurrent.futures
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.serving import Rejected, ServingConfig, ServingRuntime
    from bigdl_tpu.utils.checkpoint import save_checkpoint

    model = LeNet5(10)
    params, state, _ = model.build(jax.random.PRNGKey(0), (1, 28, 28, 1))

    rs = np.random.RandomState(0)
    example = rs.rand(1, 28, 28, 1).astype("float32")
    runtime = ServingRuntime(
        model, params, state, example_input=example,
        config=ServingConfig(buckets=(1, 8, 32), max_wait_ms=3.0,
                             capacity=256, default_deadline_ms=5_000.0))

    # -- phase 1: 64 concurrent single-image requests ----------------------
    images = [rs.rand(1, 28, 28, 1).astype("float32") for _ in range(64)]
    with concurrent.futures.ThreadPoolExecutor(16) as pool:
        results = list(pool.map(runtime.predict, images))
    preds = [int(np.asarray(r).argmax(-1)[0]) for r in results]
    print(f"phase 1: {len(results)} concurrent b1 requests -> "
          f"{runtime.metrics.batches} device batches, "
          f"{runtime.compile_count()} compiled shapes, preds[:8]={preds[:8]}")

    # -- phase 2: hot-swap a checkpoint while requests are in flight -------
    params2, state2, _ = model.build(jax.random.PRNGKey(1), (1, 28, 28, 1))
    with tempfile.TemporaryDirectory() as tmp:
        ckpt = save_checkpoint(tmp, step=1, params=params2, model_state=state2)
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            futs = [pool.submit(runtime.predict, img) for img in images]
            runtime.swap_checkpoint("v1", ckpt)
            done = sum(1 for f in futs if f.result() is not None)
    print(f"phase 2: hot-swapped to {runtime.active_version!r} under load, "
          f"{done}/{len(images)} requests served (zero dropped)")

    # -- phase 3: overload -> graceful admission rejection -----------------
    tiny = ServingRuntime(model, params, state, example_input=example,
                          config=ServingConfig(buckets=(1, 8), max_wait_ms=1.0,
                                               capacity=4))
    rejected = 0
    futures = []
    for img in images:
        try:
            futures.append(tiny.submit(img))
        except Rejected:
            rejected += 1
    for f in futures:
        f.result(timeout=30)
    print(f"phase 3: capacity-4 queue under a 64-request burst -> "
          f"{rejected} rejected at admission, {len(futures)} served")
    tiny.close()

    runtime.close()  # drains in-flight batches
    snap = runtime.metrics.snapshot()
    print(f"latency p50/p99: {snap['latency_ms']['p50']}/"
          f"{snap['latency_ms']['p99']} ms, "
          f"occupancy {snap['batch_occupancy']}, "
          f"queue peak {snap['queue_depth_peak']}")


if __name__ == "__main__":
    main()
