"""Model-as-UDF over a dataframe (reference: example/udfpredictor — a
Spark-SQL UDF that classifies text columns with a trained model).

Trains a small text classifier, registers it as a prediction function, and
applies it as a column UDF on a pandas frame — the TPU-side analogue of
`df.withColumn("class", udf(col))` serving (batched under the hood via
PredictionService, not row-at-a-time).

    python examples/udf_predictor.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SEQ_LEN = 16
VOCAB = 50
CLASSES = 3


def featurize(text):
    """Token-hash featurizer (stand-in for the reference's GloVe path)."""
    ids = [hash(w) % (VOCAB - 1) + 1 for w in text.lower().split()][:SEQ_LEN]
    return np.asarray(ids + [0] * (SEQ_LEN - len(ids)), np.int32)


def main(argv=None):
    import jax
    import pandas as pd

    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import Adagrad, PredictionService

    # toy corpus: each class has a marker word the model can learn
    markers = ["alpha", "beta", "gamma"]
    rs = np.random.RandomState(0)
    rows = []
    for _ in range(300):
        c = rs.randint(CLASSES)
        filler = " ".join(f"w{rs.randint(40)}" for _ in range(6))
        rows.append((f"{markers[c]} {filler}", c))
    df = pd.DataFrame(rows, columns=["text", "label"])

    model = nn.Sequential(
        nn.LookupTable(VOCAB, 16),
        nn.TemporalConvolution(16, 32, 3), nn.ReLU(),
        nn.Max(dimension=1),  # max-over-time pooling
        nn.Linear(32, CLASSES), nn.LogSoftMax())
    params, state, _ = model.build(jax.random.PRNGKey(0), (32, SEQ_LEN))
    crit = nn.ClassNLLCriterion()
    optim = Adagrad(learning_rate=0.2)
    opt_state = optim.init(params)

    x = np.stack([featurize(t) for t in df["text"]])
    y = df["label"].to_numpy()

    @jax.jit
    def step(p, os_, xb, yb):
        def loss_fn(p):
            out, _ = model.apply(p, state, xb, training=True)
            return crit.forward(out, yb)

        loss, g = jax.value_and_grad(loss_fn)(p)
        p2, os2 = optim.step(g, p, os_)
        return p2, os2, loss

    for epoch in range(6):
        for off in range(0, 288, 32):
            params, opt_state, loss = step(params, opt_state,
                                           x[off:off + 32], y[off:off + 32])
    print(f"trained: final loss {float(loss):.4f}")

    # --- the "UDF": a callable column transform backed by the service -----
    service = PredictionService(model, params, state, concurrency=2)

    def predict_udf(texts):
        feats = np.stack([featurize(t) for t in texts])
        return np.argmax(service.predict(feats), axis=-1)

    df["predicted"] = predict_udf(df["text"])
    acc = float((df["predicted"] == df["label"]).mean())
    print(f"UDF column accuracy: {acc:.3f}")
    print(df.head(3)[["text", "label", "predicted"]].to_string(index=False))
    return acc


if __name__ == "__main__":
    main()
