"""Load a Caffe model (prototxt + binary .caffemodel) AND a Torch .t7,
run inference, then the serving pipeline: fold conv+BN, int8-quantize,
save native (reference: example/loadmodel — its Test entry loads Caffe /
Torch / BigDL models and evaluates; utils/caffe/CaffeLoader.scala,
utils/TorchFile.scala, ConvertModel --quantize).

Without --prototxt the example is self-contained: it builds a small
conv+BN net, writes a REAL binary .caffemodel + prototxt pair with
save_caffe, and loads that back.

    python examples/caffe_loadmodel.py \
        [--prototxt net.prototxt --caffemodel net.caffemodel] \
        [--quantize dynamic|static|weight_only|auto] [--out ./served]
"""

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

SHAPE = (8, 16, 16, 3)
CLASSES = 5


def export_demo_caffe(proto_path, weights_path):
    """A conv+BN+fc net saved as prototxt + BINARY caffemodel."""
    import jax

    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils.caffe import save_caffe

    m = nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1, with_bias=False),
        nn.SpatialBatchNormalization(8), nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Flatten(),
        nn.Linear(8 * (SHAPE[1] // 2) * (SHAPE[2] // 2), CLASSES),
        nn.SoftMax())
    p, s, _ = m.build(jax.random.PRNGKey(0), SHAPE)
    save_caffe(m, p, s, proto_path, weights_path, input_shape=SHAPE)
    return proto_path, weights_path


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--prototxt", default=None)
    ap.add_argument("--caffemodel", default=None)
    ap.add_argument("--quantize", default="dynamic",
                    choices=("dynamic", "static", "weight_only", "auto"))
    ap.add_argument("--out", default=None, help="native save dir")
    args = ap.parse_args(argv)

    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils.caffe import load_caffe
    from bigdl_tpu.utils.fusion import fold_batchnorm
    from bigdl_tpu.utils.serializer import save_model
    from bigdl_tpu.utils.torchfile import load_t7, save_t7

    tmp = tempfile.mkdtemp(prefix="caffe_loadmodel_")
    proto, weights = args.prototxt, args.caffemodel
    if proto is None:
        proto, weights = export_demo_caffe(
            os.path.join(tmp, "net.prototxt"),
            os.path.join(tmp, "net.caffemodel"))
        print(f"exported demo caffe pair under {tmp}")

    # --- 1. load + predict (reference: loadmodel Caffe leg) ------------
    model, params, state = load_caffe(proto, weights)
    rs = np.random.RandomState(0)
    x = rs.rand(*SHAPE).astype(np.float32)
    t0 = time.perf_counter()
    probs, _ = model.apply(params, state, jnp.asarray(x), training=False)
    probs = np.asarray(probs)
    print(f"caffe model loaded: {probs.shape[0]} predictions in "
          f"{(time.perf_counter() - t0) * 1e3:.1f}ms, "
          f"top-1 classes {np.argmax(probs, -1).tolist()}")

    # --- 2. Torch .t7 leg (reference: loadmodel Torch leg) -------------
    from bigdl_tpu.utils.interop import export_torch_state_dict, \
        import_torch_state_dict

    t7 = os.path.join(tmp, "weights.t7")
    save_t7(t7, {k: np.asarray(v)
                 for k, v in export_torch_state_dict(
                     model, params, state).items()})
    restored = load_t7(t7)
    params2, state2 = import_torch_state_dict(model, params, state,
                                              dict(restored))
    probs2, _ = model.apply(params2, state2, jnp.asarray(x), training=False)
    drift = float(np.max(np.abs(np.asarray(probs2) - probs)))
    print(f"torch .t7 round trip: {len(restored)} tensors, "
          f"max prediction drift {drift:.2e}")

    # --- 3. serving pipeline: fold BN, quantize, save ------------------
    fm, fp, fs = fold_batchnorm(model, params, state)
    fold_probs, _ = fm.apply(fp, fs, jnp.asarray(x), training=False)
    print(f"conv+BN folded: max drift "
          f"{float(np.max(np.abs(np.asarray(fold_probs) - probs))):.2e}")

    if args.quantize == "auto":
        qm, qp = nn.quantize(fm, fp, mode="auto", sample_input=x, state=fs)
        rep = qm._quant_auto_report
        print(f"quantize auto picked {rep['picked']!r}: "
              f"{ {k: round(v, 2) for k, v in rep['ms_per_batch'].items()} }")
    else:
        qm, qp = nn.quantize(fm, fp, mode=args.quantize)
        if args.quantize == "static":
            qp = nn.calibrate(qm, qp, fs, [x])
    q_probs, _ = qm.apply(qp, fs, jnp.asarray(x), training=False)
    agree = float(np.mean(np.argmax(np.asarray(q_probs), -1)
                          == np.argmax(probs, -1)))
    print(f"int8 ({args.quantize}): top-1 agreement with float "
          f"{agree:.0%}")

    out = args.out or os.path.join(tmp, "served")
    save_model(out, qm, qp, fs)
    print(f"saved serving model to {out}")
    return probs


if __name__ == "__main__":
    main()
