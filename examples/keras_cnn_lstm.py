"""IMDB-style sentiment model through the Keras-1 API: Embedding ->
Conv1D -> MaxPooling1D -> LSTM -> Dense(sigmoid).

Reference: pyspark/bigdl/examples/keras/imdb_cnn_lstm.py (the same stack
trained via the keras compile/fit front end).  Without --data-dir it
synthesizes class-dependent token streams so the example runs in seconds.

    python examples/keras_cnn_lstm.py [--epochs 2]
"""

from __future__ import annotations

import argparse

import numpy as np


def build_model(vocab_size: int, seq_len: int):
    from bigdl_tpu import keras

    return keras.Sequential(
        keras.Embedding(vocab_size, 32, input_shape=(seq_len,)),
        keras.Convolution1D(32, 5, activation="relu"),
        keras.MaxPooling1D(2),
        keras.LSTM(32),
        keras.Dense(1, activation="sigmoid"),
    )


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--vocab-size", type=int, default=1000)
    ap.add_argument("--samples", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=2)
    args = ap.parse_args(argv)

    rs = np.random.RandomState(0)
    half = args.vocab_size // 2
    x = np.zeros((args.samples, args.seq_len), np.int32)
    y = np.zeros((args.samples,), np.float32)
    for i in range(args.samples):
        cls = i % 2
        lo = 1 + cls * half
        x[i] = rs.randint(lo, lo + half - 1, args.seq_len)
        y[i] = cls

    model = build_model(args.vocab_size, args.seq_len)
    model.compile(optimizer="adam", loss="binary_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y[:, None], batch_size=args.batch_size,
              nb_epoch=args.epochs)
    results = model.evaluate(x, y[:, None], batch_size=args.batch_size)
    for name, value in results:
        print(f"{name}: {value:.4f}")
    return dict(results)


if __name__ == "__main__":
    main()
