"""PTB-style LSTM language model (reference: example/languagemodel +
models/rnn/Train.scala:48-59).

Trains the stacked-LSTM LM on a real tokenized corpus when --data is a
text file, else on a synthetic token stream; reports per-word perplexity.

    python examples/language_model.py [--data ptb.train.txt] [--epochs 1]
"""

import argparse
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def load_ids(path, vocab_size):
    from bigdl_tpu.dataset.text import Dictionary

    with open(path) as f:
        words = f.read().replace("\n", " <eos> ").split()
    d = Dictionary([words], vocab_size=vocab_size)
    ids = np.asarray([d.get_index(w) for w in words], np.int32)
    return ids, d.vocab_size()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None, help="tokenized corpus text file")
    ap.add_argument("--vocab-size", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--num-steps", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--tokens", type=int, default=20_000,
                    help="synthetic stream length when no --data")
    args = ap.parse_args(argv)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, MiniBatch
    from bigdl_tpu.dataset.text import ptb_stream_batches
    from bigdl_tpu.models import PTBModel
    from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger

    if args.data:
        ids, vocab = load_ids(args.data, args.vocab_size)
    else:  # synthetic markov-ish stream so the example always runs
        rs = np.random.RandomState(0)
        vocab = args.vocab_size
        ids = np.cumsum(rs.randint(1, 4, args.tokens)) % vocab

    batches = [MiniBatch(x, y) for x, y in
               ptb_stream_batches(ids, args.batch_size, args.num_steps)]
    print(f"{len(ids)} tokens, vocab {vocab}, {len(batches)} batches/epoch")

    model = PTBModel(vocab_size=vocab, embedding_dim=args.hidden,
                     hidden_size=args.hidden, num_layers=args.layers,
                     keep_prob=0.9)
    # LM loss: NLL at every timestep, averaged over B and T so the loss is
    # per-token and perplexity is exp(loss)
    criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                            size_average=True)

    opt = LocalOptimizer(model, DataSet.array(batches), criterion,
                         optim_method=SGD(learning_rate=0.5, momentum=0.9),
                         end_trigger=Trigger.max_epoch(args.epochs))
    opt.optimize()
    loss = opt._driver_state["loss"]
    print(f"final loss {loss:.4f}  perplexity {math.exp(min(loss, 20.0)):.1f}")
    return loss


if __name__ == "__main__":
    main()
