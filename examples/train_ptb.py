"""BASELINE config 5: PTB-style stacked-LSTM language model trained on the
TPU to a stated held-out perplexity, end-to-end through DistriOptimizer
with checkpoints and TensorBoard summaries.

Reference: models/rnn/Train.scala:48-59 + example/languagemodel/
PTBWordLM.scala (SequencePreprocess -> PTBModel -> Optimizer with
TimeDistributedCriterion(CrossEntropy)).

Data: `python tools/gen_ptb.py --out data/ptb` writes PTB-format
ptb.{train,valid,test}.txt built from real English prose (installed
package docstrings — see that script's docstring; it is real natural
language but NOT the Penn Treebank, so perplexities are comparable only
within this corpus).

Training recipe is the classic PTB one (Zaremba et al. as used by the
reference's example): SGD lr 1.0, gradient L2-clip 5, lr halves each
epoch after a flat start, dropout between LSTM layers.

    python examples/train_ptb.py --data-dir data/ptb --epochs 8 \
        --checkpoint /tmp/ptb_ckpt --summary /tmp/ptb_summary

Prints one JSON line {valid_ppl, test_ppl, wall_s, tok_per_s, epochs}.
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def load_split(path, d):
    with open(path, encoding="utf-8") as f:
        words = f.read().replace("\n", " <eos> ").split()
    return np.asarray([d.get_index(w) for w in words], np.int32)


def to_dataset(ids, batch_size, num_steps, device_resident=True):
    import jax.numpy as jnp

    from bigdl_tpu.dataset import ArrayDataSet, MiniBatch
    from bigdl_tpu.dataset.text import ptb_stream_batches

    items = []
    for x, y in ptb_stream_batches(ids, batch_size, num_steps):
        if device_resident:
            x, y = jnp.asarray(x), jnp.asarray(y)
        items.append(MiniBatch(x, y))
    return ArrayDataSet(items)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default="data/ptb")
    ap.add_argument("--vocab-size", type=int, default=10_000)
    ap.add_argument("--embed", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--keep-prob", type=float, default=0.75)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-steps", type=int, default=35)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--flat-epochs", type=int, default=3,
                    help="epochs at full lr before halving per epoch")
    ap.add_argument("--clip", type=float, default=5.0)
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--summary", default=None)
    args = ap.parse_args(argv)

    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset.text import Dictionary
    from bigdl_tpu.models import PTBModel
    from bigdl_tpu.optim import DistriOptimizer, Loss, SGD, Trigger
    from bigdl_tpu.optim.schedules import EpochDecay
    from bigdl_tpu.utils.summary import TrainSummary, ValidationSummary

    # vocabulary from the train split only (PTB convention; the corpus
    # already maps rare words to <unk> so this is just word->id)
    with open(os.path.join(args.data_dir, "ptb.train.txt"), encoding="utf-8") as f:
        train_words = f.read().replace("\n", " <eos> ").split()
    d = Dictionary([train_words], vocab_size=args.vocab_size + 2)
    vocab = d.vocab_size()

    ids = {}
    for split in ("train", "valid", "test"):
        ids[split] = load_split(
            os.path.join(args.data_dir, f"ptb.{split}.txt"), d)
    print(f"vocab {vocab}; tokens train/valid/test: "
          f"{len(ids['train'])}/{len(ids['valid'])}/{len(ids['test'])}")

    train_ds = to_dataset(ids["train"], args.batch_size, args.num_steps)
    valid_ds = to_dataset(ids["valid"], args.batch_size, args.num_steps)
    test_ds = to_dataset(ids["test"], args.batch_size, args.num_steps)

    model = PTBModel(vocab_size=vocab, embedding_dim=args.embed,
                     hidden_size=args.hidden, num_layers=args.layers,
                     keep_prob=args.keep_prob)
    criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                            size_average=True)

    # lr * 0.5^(epoch - flat) after the flat epochs (0.1^(x*log10 2))
    flat = args.flat_epochs
    sched = EpochDecay(lambda e: jnp.maximum(e - flat, 0) * 0.3010299957)
    optimizer = DistriOptimizer(
        model, train_ds, criterion,
        optim_method=SGD(learning_rate=args.lr, schedule=sched),
        end_trigger=Trigger.max_epoch(args.epochs))
    optimizer.set_gradient_clipping_by_l2_norm(args.clip)
    optimizer.set_validation(Trigger.every_epoch(), valid_ds,
                             [Loss(criterion)])
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, Trigger.every_epoch())
    if args.summary:
        optimizer.set_train_summary(TrainSummary(args.summary, "ptb"))
        optimizer.set_val_summary(ValidationSummary(args.summary, "ptb"))

    t0 = time.time()
    optimizer.optimize()
    wall = time.time() - t0

    def ppl(ds):
        optimizer.val_dataset = ds
        loss = optimizer.validate()[0].result()[0]
        return math.exp(min(loss, 20.0))

    valid_ppl = ppl(valid_ds)
    test_ppl = ppl(test_ds)
    n_tok = train_ds.size() * args.batch_size * args.num_steps * args.epochs
    out = {"config": "ptb_lstm", "valid_ppl": round(valid_ppl, 2),
           "test_ppl": round(test_ppl, 2),
           "vocab": vocab, "epochs": args.epochs,
           "hidden": args.hidden, "layers": args.layers,
           "wall_s": round(wall, 1), "tok_per_s": round(n_tok / wall, 0),
           "corpus": "docstring-prose (real English, not Penn Treebank)"}
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
