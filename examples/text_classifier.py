"""Text classification with embeddings + temporal convolution.

Reference: example/textclassification (GloVe embeddings + CNN over News20).
Uses real News20 + GloVe files when --data-dir/--glove are given; otherwise
trains on a synthetic token corpus so the example always runs.

    python examples/text_classifier.py [--data-dir news20/ --glove glove.6B.100d.txt]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_model(vocab_size, embed_dim, seq_len, n_classes,
                embeddings=None):
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn

    flat = 128 * ((seq_len - 4) // 5 - 4)
    if flat <= 0:
        raise ValueError(f"seq_len={seq_len} too short for the conv stack "
                         f"(2x conv5 + pool5 needs seq_len >= 29)")
    model = nn.Sequential(
        nn.LookupTable(vocab_size, embed_dim),
        nn.TemporalConvolution(embed_dim, 128, 5), nn.ReLU(),
        nn.TemporalMaxPooling(5, 5),
        nn.TemporalConvolution(128, 128, 5), nn.ReLU(),
        nn.Flatten(),
        nn.Linear(flat, 128), nn.ReLU(),
        nn.Linear(128, n_classes), nn.LogSoftMax())
    return model


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--glove", default=None)
    ap.add_argument("--seq-len", type=int, default=100)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=2)
    args = ap.parse_args(argv)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import ArrayDataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.text import SentenceTokenizer
    from bigdl_tpu.optim import LocalOptimizer, Adam, Top1Accuracy, Trigger

    vocab_size, embed_dim, n_classes = 2000, 50, 4
    rs = np.random.RandomState(0)
    if args.data_dir:
        from bigdl_tpu.dataset import load_news20
        from bigdl_tpu.dataset.text import Dictionary

        texts = load_news20(args.data_dir)
        n_classes = max(t[1] for t in texts) + 1
        tok = SentenceTokenizer()
        token_lists = [next(tok(iter([t[0]]))) for t in texts]
        d = Dictionary(token_lists, vocab_size=vocab_size - 1)
        vocab_size = d.vocab_size()
        ids = [np.asarray(d.encode(t[:args.seq_len]), np.int32) for t in token_lists]
        ids = [np.pad(i, (0, args.seq_len - len(i))) for i in ids]
        labels = [t[1] for t in texts]
    else:
        print("no --data-dir: synthetic class-dependent token streams")
        ids, labels = [], []
        for i in range(512):
            c = i % n_classes
            # class-c documents favor a distinct token band
            band = rs.randint(c * 400, c * 400 + 400, args.seq_len)
            ids.append(band.astype(np.int32))
            labels.append(c)

    samples = [Sample.from_ndarray(x, np.int32(y)) for x, y in zip(ids, labels)]
    ds = ArrayDataSet(samples).transform(SampleToMiniBatch(args.batch_size))
    model = build_model(vocab_size, embed_dim, args.seq_len, n_classes)
    optimizer = LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                               optim_method=Adam(learning_rate=1e-3),
                               end_trigger=Trigger.max_epoch(args.epochs))
    optimizer.set_validation(Trigger.every_epoch(), ds, [Top1Accuracy()])
    optimizer.optimize()
    for res in optimizer.validate():
        print("validation:", res)


if __name__ == "__main__":
    main()
