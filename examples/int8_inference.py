"""Int8 quantized inference: calibrate once, serve faster than bf16.

Reference flow: train fp32 -> `module.quantize()` -> serve int8
(nn/quantized/Quantizer.scala:27-32).  Here the quantizer is functional
and mode-aware (nn/quantized.py): `static` mode + `calibrate()` gives the
measured 1.26x-over-bf16 ResNet-50 inference path (BENCH_APPENDIX.md);
`weight_only` wraps whole models for bandwidth-bound decode.

  python examples/int8_inference.py
"""

import numpy as np

import jax
import jax.numpy as jnp


def main():
    import bigdl_tpu.nn as nn

    # a small trained-ish conv net
    model = nn.Sequential(
        nn.SpatialConvolution(3, 16, 3, 3, 1, 1, 1, 1), nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.SpatialConvolution(16, 32, 3, 3, 1, 1, 1, 1), nn.ReLU(),
        nn.GlobalAveragePooling2D(), nn.Linear(32, 10), nn.LogSoftMax())
    params, state, _ = model.build(jax.random.PRNGKey(0), (8, 32, 32, 3))
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(8, 32, 32, 3), jnp.float32)
    y_fp, _ = model.apply(params, state, x)

    # 1. static int8: calibrate activation scales on real batches, then the
    #    jitted forward runs the int8 MXU path with no runtime reduce
    qmodel, qparams = nn.quantize(model, params, mode="static")
    calib_batches = [jnp.asarray(rs.rand(8, 32, 32, 3), jnp.float32)
                     for _ in range(4)]
    qparams = nn.calibrate(qmodel, qparams, state, calib_batches)
    fwd = jax.jit(lambda p, s, xx: qmodel.apply(p, s, xx)[0])
    y_q8 = fwd(qparams, state, x)
    drift = float(jnp.max(jnp.abs(jnp.exp(y_q8) - jnp.exp(y_fp))))
    print(f"static int8: max probability drift vs fp32 = {drift:.4f}")
    assert drift < 0.05

    # 2. weight-only int8: wrap ANY module; activations stay float,
    #    weights stream from HBM at int8 width
    wmodel, wparams = nn.WeightOnlyInt8.from_float(model, params,
                                                   min_size=256)
    y_w8, _ = wmodel.apply(wparams, state, x)
    drift_w = float(jnp.max(jnp.abs(jnp.exp(y_w8) - jnp.exp(y_fp))))
    print(f"weight-only int8: max probability drift vs fp32 = {drift_w:.4f}")
    assert drift_w < 0.05

    def nbytes(t):
        return sum(l.size * l.dtype.itemsize
                   for l in jax.tree_util.tree_leaves(t))

    print(f"weight bytes: fp32 {nbytes(params)}, weight-only int8 "
          f"{nbytes(wparams)} ({nbytes(wparams) / nbytes(params):.2f}x)")


if __name__ == "__main__":
    main()
